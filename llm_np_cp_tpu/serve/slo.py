"""SLO accounting for the serving fleet: goodput, burn rates, anomalies.

Once raw tok/s plateaus (the stack is bandwidth-bound — see "Ragged
Paged Attention", PAPERS.md), the number left to optimize is whether
requests actually *met their latency targets*.  This module makes that
first-class:

- ``SLOPolicy(ttft_s, tpot_s)`` — the per-request targets: time to
  first token and time per output token (the steady decode cadence).
  A request MEETS the SLO when every observable target holds; an
  aborted request is always a miss (it failed to deliver, whatever the
  reason), and a request recovered with no timestamps at all (a
  ``finish_recovered`` terminal — only its finish event survived a
  crash) is ``untimed``: excluded from attainment rather than guessed.
- ``SLOTracker`` — per-engine accounting, fed from
  ``ServeMetrics._record_latencies`` under the metrics lock:
  ``slo_attainment`` (fraction of timed terminals meeting the policy),
  ``goodput_tok_s`` (tokens of SLO-attaining requests / traffic span —
  the tokens that were worth serving), and multi-window error-budget
  BURN RATES (5m/1h): observed miss rate over the window divided by the
  budgeted miss rate ``1 - target``.  Burn > 1 means the error budget
  is being spent faster than planned — the standard SRE paging signal,
  here computed from bucketed ring counters so a week-long server pays
  O(buckets) memory, not O(requests).
- ``TickSentinel`` — rolling per-phase EWMA baselines over the engine's
  tick-phase slices (``MIXED_TICK_PHASES`` / ``TICK_PHASES``).  An
  outlier tick names the guilty phase — turning "p99 got worse" into
  "host_sync regressed at tick 1204" — via a trace instant and the
  ``llm_serve_anomaly_ticks_total{phase=}`` counter.

ZERO-OVERHEAD WHEN OFF (the FaultInjector/TraceRecorder discipline,
pinned by tools/lint R4): nothing constructs a policy/tracker/sentinel
unless requested (``--slo-ttft``/``--slo-tpot``/``--tick-sentinel``),
and every hook is a single ``is None`` check.  Everything here is
host-side Python — attaching SLO accounting adds zero jit recompiles.

THREAD SAFETY: ``SLOTracker`` is mutated only under the owning
``ServeMetrics`` lock (its caller ``_record_latencies`` is a
lock-assumed helper); reads copy scalars.  ``TickSentinel`` is
engine-thread-only state, like the scheduler.
"""

from __future__ import annotations

import dataclasses
import time
from collections import Counter
from typing import Any, Callable

# (label, window seconds, bucket count) — the standard multi-window
# burn-rate pair: a fast window that catches a cliff and a slow one
# that catches a smolder.  Bucketed so memory is O(buckets) forever.
BURN_WINDOWS: tuple[tuple[str, float, int], ...] = (
    ("5m", 300.0, 30),
    ("1h", 3600.0, 60),
)


@dataclasses.dataclass(frozen=True)
class SLOPolicy:
    """Per-request latency targets.  ``None`` disables that target;
    ``target`` is the attainment objective the burn rate reads its
    error budget from (0.99 → 1% of requests may miss)."""

    ttft_s: float | None = None
    tpot_s: float | None = None
    target: float = 0.99

    def __post_init__(self) -> None:
        if self.ttft_s is not None and self.ttft_s <= 0:
            raise ValueError(f"ttft_s must be > 0, got {self.ttft_s}")
        if self.tpot_s is not None and self.tpot_s <= 0:
            raise ValueError(f"tpot_s must be > 0, got {self.tpot_s}")
        if not (0.0 < self.target < 1.0):
            raise ValueError(
                f"target must be in (0, 1), got {self.target}"
            )

    # ------------------------------------------------------------------
    def verdict(self, req: Any) -> "SLOVerdict":
        """Judge one terminal request from its own timestamps.  Pure —
        the request log and the metrics tracker both call this and must
        agree.  TTFT uses the same base as ServeMetrics (the wall
        arrival when the realtime replay recorded one, else submit)."""
        ttft = tpot = None
        if req.submit_time is not None and req.first_token_time is not None:
            base = req.extra.get("arrival_wall", req.submit_time)
            ttft = req.first_token_time - base
        n_after = len(req.generated) - 1
        if (
            req.first_token_time is not None
            and req.finish_time is not None
            and n_after > 0
        ):
            tpot = (req.finish_time - req.first_token_time) / n_after
        timed = ttft is not None or tpot is not None
        ttft_ok = (
            None if ttft is None or self.ttft_s is None
            else ttft <= self.ttft_s
        )
        tpot_ok = (
            None if tpot is None or self.tpot_s is None
            else tpot <= self.tpot_s
        )
        aborted = req.finish_reason == "aborted"
        ok = (
            not aborted
            and timed
            and ttft_ok is not False
            and tpot_ok is not False
        )
        return SLOVerdict(ok=ok, timed=timed,
                          ttft_ok=ttft_ok, tpot_ok=tpot_ok,
                          ttft_s=ttft, tpot_s=tpot)


@dataclasses.dataclass(frozen=True)
class SLOVerdict:
    ok: bool
    timed: bool  # False → untimed: excluded from attainment entirely
    ttft_ok: bool | None  # None = target off or latency unobservable
    tpot_ok: bool | None
    ttft_s: float | None
    tpot_s: float | None

    def to_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {"ok": self.ok, "timed": self.timed}
        if self.ttft_s is not None:
            out["ttft_s"] = round(self.ttft_s, 6)
            if self.ttft_ok is not None:
                out["ttft_ok"] = self.ttft_ok
        if self.tpot_s is not None:
            out["tpot_s"] = round(self.tpot_s, 6)
            if self.tpot_ok is not None:
                out["tpot_ok"] = self.tpot_ok
        return out


class RollingWindow:
    """Bucketed (total, miss) counters over a sliding time window.

    ``add(t, ok)`` lands in bucket ``int(t / bucket_s)``; a bucket is
    lazily reset when its slot is reused for a newer period, and
    ``totals(t)`` sums only buckets whose period is still inside the
    window — so the estimate is exact to bucket granularity with O(1)
    writes and O(buckets) reads/memory, whatever the traffic rate.
    """

    def __init__(self, span_s: float, n_buckets: int) -> None:
        if span_s <= 0 or n_buckets < 1:
            raise ValueError(
                f"bad window span_s={span_s} n_buckets={n_buckets}"
            )
        self.span_s = span_s
        self.bucket_s = span_s / n_buckets
        self.n = n_buckets
        # slot → [period index, total, miss]
        self._buckets = [[-1, 0, 0] for _ in range(n_buckets)]

    def _slot(self, t: float) -> list:
        period = int(t // self.bucket_s)
        b = self._buckets[period % self.n]
        if b[0] != period:
            b[0], b[1], b[2] = period, 0, 0
        return b

    def add(self, t: float, ok: bool) -> None:
        b = self._slot(t)
        b[1] += 1
        if not ok:
            b[2] += 1

    def totals(self, t: float) -> tuple[int, int]:
        """→ ``(total, miss)`` over the window ending at ``t``."""
        lo = int(t // self.bucket_s) - self.n + 1
        total = miss = 0
        for period, n, bad in self._buckets:
            if period >= lo and period >= 0:
                total += n
                miss += bad
        return total, miss


class SLOTracker:
    """Per-engine SLO accounting: verdict counters, goodput tokens, and
    the multi-window burn-rate rings.  Mutated ONLY under the owning
    ``ServeMetrics`` lock (``observe`` is called from the lock-assumed
    ``_record_latencies``); ``snapshot`` copies scalars, so a racy read
    sees a consistent-enough point-in-time view (counters are ints)."""

    def __init__(
        self,
        policy: SLOPolicy,
        *,
        clock: Callable[[], float] = time.perf_counter,
        windows: tuple[tuple[str, float, int], ...] = BURN_WINDOWS,
    ) -> None:
        self.policy = policy
        self.clock = clock
        self.n_ok = 0
        self.n_miss = 0
        self.n_untimed = 0
        self.goodput_tokens = 0
        self.t_first: float | None = None
        self.t_last: float | None = None
        self.windows = {
            label: RollingWindow(span, buckets)
            for label, span, buckets in windows
        }

    # -- record (caller holds the ServeMetrics lock) -------------------
    def observe(self, req: Any, now: float | None = None) -> SLOVerdict:
        v = self.policy.verdict(req)
        now = self.clock() if now is None else now
        if not v.timed and req.finish_reason != "aborted":
            # nothing observable and it wasn't aborted (a recovered
            # terminal whose timestamps died with the old process):
            # excluded from attainment rather than guessed.  Aborts
            # always count — timed or not, they failed to deliver
            self.n_untimed += 1
            return v
        if self.t_first is None:
            self.t_first = now
        self.t_last = now
        if v.ok:
            self.n_ok += 1
            self.goodput_tokens += len(req.generated)
        else:
            self.n_miss += 1
        for w in self.windows.values():
            w.add(now, v.ok)
        return v

    # -- read ----------------------------------------------------------
    def burn_rate(self, label: str, now: float | None = None) -> float:
        """Observed miss rate over the window / budgeted miss rate.
        1.0 = spending the error budget exactly as planned; 0 traffic =
        0 burn (nothing is being spent)."""
        now = self.clock() if now is None else now
        total, miss = self.windows[label].totals(now)
        if total == 0:
            return 0.0
        return (miss / total) / (1.0 - self.policy.target)

    def snapshot(self, now: float | None = None) -> dict[str, Any]:
        now = self.clock() if now is None else now
        timed = self.n_ok + self.n_miss
        span = (
            (self.t_last - self.t_first)
            if self.t_first is not None and self.t_last is not None
            else 0.0
        )
        out: dict[str, Any] = {
            "policy": {
                "ttft_s": self.policy.ttft_s,
                "tpot_s": self.policy.tpot_s,
                "target": self.policy.target,
            },
            "slo_ok": self.n_ok,
            "slo_miss": self.n_miss,
            "slo_untimed": self.n_untimed,
            "goodput_tokens": self.goodput_tokens,
            "goodput_tok_s": (
                self.goodput_tokens / span if span > 0 else 0.0
            ),
        }
        if timed:
            out["slo_attainment"] = self.n_ok / timed
        for label in self.windows:
            out[f"slo_burn_rate_{label}"] = self.burn_rate(label, now)
        return out


def aggregate_slo(trackers: list[SLOTracker | None]) -> dict[str, Any]:
    """Fleet aggregation for ``GET /debug/slo``: summed verdict/goodput
    counters and burn rates recomputed from the SUMMED window totals (a
    mean of per-replica ratios would weight an idle replica like a
    loaded one)."""
    live = [t for t in trackers if t is not None]
    if not live:
        return {}
    now = live[0].clock()
    ok = sum(t.n_ok for t in live)
    miss = sum(t.n_miss for t in live)
    spans = [
        t.t_last - t.t_first
        for t in live
        if t.t_first is not None and t.t_last is not None
    ]
    span = max(spans, default=0.0)
    goodput = sum(t.goodput_tokens for t in live)
    out: dict[str, Any] = {
        "policy": {
            "ttft_s": live[0].policy.ttft_s,
            "tpot_s": live[0].policy.tpot_s,
            "target": live[0].policy.target,
        },
        "slo_ok": ok,
        "slo_miss": miss,
        "slo_untimed": sum(t.n_untimed for t in live),
        "goodput_tokens": goodput,
        "goodput_tok_s": goodput / span if span > 0 else 0.0,
    }
    if ok + miss:
        out["slo_attainment"] = ok / (ok + miss)
    for label in live[0].windows:
        total = bad = 0
        for t in live:
            n, b = t.windows[label].totals(now)
            total += n
            bad += b
        out[f"slo_burn_rate_{label}"] = (
            (bad / total) / (1.0 - live[0].policy.target) if total else 0.0
        )
    return out


class TickSentinel:
    """Rolling per-phase anomaly detector over the engine's tick-phase
    slices.

    Each phase keeps an EWMA mean and an EWMA of absolute deviation
    (cheap, outlier-resistant).  After ``warmup_ticks`` observations a
    phase whose duration exceeds ``mean + threshold * max(dev, jitter
    floor)`` is an OUTLIER: ``observe`` returns the offenders sorted
    guiltiest-first so the engine can stamp a trace instant naming the
    phase and bump ``anomaly_ticks_total{phase=}``.  Outlier samples
    update the baseline CLAMPED to the detection bound — a one-tick
    spike barely moves it, while a persistent regression re-baselines
    within ~1/alpha ticks instead of firing forever.

    Engine-thread-only state (like the scheduler); ``anomalies`` is a
    plain Counter the engine folds into ServeMetrics under its lock.
    """

    def __init__(
        self,
        *,
        alpha: float = 0.05,
        threshold: float = 8.0,
        warmup_ticks: int = 32,
        min_us: float = 200.0,
    ) -> None:
        if not (0.0 < alpha <= 1.0):
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        if threshold <= 0:
            raise ValueError(f"threshold must be > 0, got {threshold}")
        self.alpha = alpha
        self.threshold = threshold
        self.warmup_ticks = warmup_ticks
        self.min_us = min_us
        self.ticks = 0
        # phase → [ewma mean us, ewma abs-dev us, samples]
        self._stats: dict[str, list[float]] = {}
        self.anomalies: Counter[str] = Counter()

    def observe(
        self, phases: tuple[tuple[str, float, float], ...],
    ) -> list[dict[str, float | str]]:
        """Fold one tick's ``(name, t0_us, t1_us)`` slices in; returns
        the outliers (possibly empty), guiltiest-first by excess over
        baseline."""
        self.ticks += 1
        out: list[dict[str, float | str]] = []
        for name, p0, p1 in phases:
            dur = max(p1 - p0, 0.0)
            st = self._stats.get(name)
            if st is None:
                self._stats[name] = [dur, 0.0, 1]
                continue
            mean, dev, n = st
            # jitter floor: microsecond-scale phases on a quiet host
            # have dev ~ 0, and without a floor every scheduler blip
            # would page
            bound = mean + self.threshold * max(dev, 0.1 * mean,
                                                self.min_us)
            is_outlier = n >= self.warmup_ticks and dur > bound
            if is_outlier:
                self.anomalies[name] += 1
                out.append({
                    "phase": name,
                    "dur_us": dur,
                    "baseline_us": mean,
                    "dev_us": dev,
                    "excess": dur / bound,
                })
                dur = bound  # clamp: spikes nudge, regressions re-baseline
            st[0] = mean + self.alpha * (dur - mean)
            st[1] = dev + self.alpha * (abs(dur - st[0]) - dev)
            st[2] = n + 1
        out.sort(key=lambda o: -float(o["excess"]))
        return out

    def baselines(self) -> dict[str, dict[str, float]]:
        """Operator view: per-phase baseline mean/dev in µs."""
        return {
            name: {"mean_us": st[0], "dev_us": st[1], "samples": st[2]}
            for name, st in self._stats.items()
        }
