"""Static preallocated KV cache.

The reference's ``KVCache`` (llama3.2_model.py:303-332) keeps per-layer
Python lists and appends by ``concatenate`` — an O(seq) copy per token per
layer with unbounded growth, and a dynamic shape XLA cannot trace.  The
TPU-native cache is a fixed-size pytree:

    k, v: [num_layers, batch, max_seq, num_kv_heads, head_dim]
    length: int32 scalar — number of tokens written (the reference's
        ``num_items()``, llama3.2_model.py:308-312) — or an int32 [B]
        vector of PER-ROW lengths (batched speculative decoding, where
        each row accepts a different number of draft tokens per round;
        writes become per-row dynamic_update_slices via vmap).

Updates are ``lax.dynamic_update_slice`` at the current offset: O(new
tokens), jit-traceable, donate-able.  The leading layer axis exists so the
model can ``lax.scan`` over layers, carrying each layer's cache slice
through as scan xs/ys.

Sequence-parallel note: the seq axis (2) is placed after batch so a
NamedSharding of P(None, "data", "seq", "model", None) shards cache slots
across chips for long-context decode (BASELINE config 5); head axis (3)
shards under tensor parallelism.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
from jax import lax

from llm_np_cp_tpu.config import ModelConfig

CAPACITY_ALIGN = 128


def align_capacity(n: int) -> int:
    """Round a requested capacity up to the framework-wide 128 contract
    (see KVCache.init docstring).  THE one definition — Generator,
    SpeculativeGenerator, and bench.py all size through this, so the
    contract can't silently diverge between production and measurement.
    """
    return -(-n // CAPACITY_ALIGN) * CAPACITY_ALIGN


class KVCache(NamedTuple):
    k: jnp.ndarray  # [L, B, S_max, K, D]
    v: jnp.ndarray  # [L, B, S_max, K, D]
    valid: jnp.ndarray  # [B, S_max] bool — written AND not a pad token
    length: jnp.ndarray  # int32 scalar
    # int8 cache mode (dtype=jnp.int8): per-token-per-head absmax scales;
    # None for float caches.  Halves cache HBM traffic for long-context
    # decode (scales are D=1/64..1/128 of the slab).
    k_scale: jnp.ndarray | None = None  # [L, B, S_max, K] f32
    v_scale: jnp.ndarray | None = None

    @classmethod
    def init(
        cls,
        config: ModelConfig,
        batch_size: int,
        max_seq_len: int,
        dtype: jnp.dtype = jnp.bfloat16,
    ) -> "KVCache":
        """Allocate zeroed slabs with capacity ``max_seq_len``.

        Capacity contract: callers that derive capacity from request
        shapes (Generator, SpeculativeGenerator) round it UP to a
        multiple of 128 before calling — unused slots cost HBM but are
        masked off by ``valid``/per-row lengths, while aligned capacities
        keep the Pallas decode kernel's kv-block size near its requested
        512 (an unaligned — worst case prime — capacity would shrink the
        largest usable divisor toward 1) and make seq-axis sharding
        divisibility automatic.  ``init`` itself honours the exact value
        it is given so tests can build odd-capacity caches on purpose.
        """
        shape = (
            config.num_hidden_layers,
            batch_size,
            max_seq_len,
            config.num_key_value_heads,
            config.head_dim,
        )
        quantized = dtype == jnp.int8
        return cls(
            k=jnp.zeros(shape, dtype=dtype),
            v=jnp.zeros(shape, dtype=dtype),
            valid=jnp.zeros((batch_size, max_seq_len), dtype=jnp.bool_),
            length=jnp.zeros((), dtype=jnp.int32),
            k_scale=jnp.zeros(shape[:-1], jnp.float32) if quantized else None,
            v_scale=jnp.zeros(shape[:-1], jnp.float32) if quantized else None,
        )

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None

    @property
    def max_seq_len(self) -> int:
        return self.k.shape[2]

    def positions(self) -> jnp.ndarray:
        """Absolute position of every cache slot: [S_max]."""
        return jnp.arange(self.max_seq_len, dtype=jnp.int32)


def truncate(cache: KVCache, new_length: jnp.ndarray) -> KVCache:
    """Logically roll the cache back to ``new_length`` tokens.

    The K/V slabs are left in place — slots ≥ new_length are marked invalid
    in the bitmap and ``length`` moves back, so subsequent writes overwrite
    them and attention (which masks on slot validity + position) never
    reads them.  O(1); the rollback primitive speculative decoding needs
    to discard rejected draft tokens.

    new_length: int32 scalar, or [B] for per-row rollback (each batch row
    keeps a different number of accepted tokens).
    """
    new_length = jnp.asarray(new_length, jnp.int32)
    bound = new_length[:, None] if new_length.ndim == 1 else new_length
    keep = jnp.arange(cache.max_seq_len, dtype=jnp.int32)[None, :] < bound
    return cache._replace(valid=cache.valid & keep, length=new_length)


def update_layer(
    k_layer: jnp.ndarray,
    v_layer: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
    offset: jnp.ndarray,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Write new keys/values at ``offset`` along the seq axis.

    k_layer/v_layer: [B, S_max, K, D]; k_new/v_new: [B, S_new, K, D];
    offset: int32 scalar (tokens already in the cache) or [B] per-row
    offsets (each row writes at its own length — vmapped update, the
    batched-speculative path).  Replaces the reference's per-layer concat
    append (llama3.2_model.py:321-330).

    Overflow contract: if ``offset + S_new > S_max`` the update start is
    silently clamped by ``dynamic_update_slice`` (XLA semantics — no
    data-dependent errors under jit), corrupting slot/position mapping.
    Callers must enforce capacity host-side; ``generate`` does.
    """
    k_new = k_new.astype(k_layer.dtype)
    v_new = v_new.astype(v_layer.dtype)
    return (
        _write_at(k_layer, k_new, offset),
        _write_at(v_layer, v_new, offset),
    )


def _write_at(slab: jnp.ndarray, new: jnp.ndarray, offset: jnp.ndarray) -> jnp.ndarray:
    """dynamic_update_slice of ``new`` into ``slab`` along the seq axis
    (axis 1 of a [B, S_max, ...] array of any trailing rank), at a scalar
    offset or per-row [B] offsets (vmapped)."""
    trail = (jnp.zeros((), jnp.int32),) * (slab.ndim - 2)
    if offset.ndim == 1:
        import jax

        return jax.vmap(
            lambda sl, nw, off: lax.dynamic_update_slice(sl, nw, (off, *trail))
        )(slab, new, offset)
    zero = jnp.zeros((), jnp.int32)
    return lax.dynamic_update_slice(slab, new, (zero, offset, *trail))


def quantize_kv(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-token-per-head symmetric int8: x [..., D] float →
    (int8 [..., D], f32 absmax/127 scale [...]).

    Same numeric contract as quant.quantize_array (weight-side int8) but
    activation-shaped: squeezed scale tuple instead of a keepdims dict,
    and the amax==0 guard keeps scale 0 (slot reads as exact zero) rather
    than mapping it to 1.  Keep the two in sync if the contract changes.
    """
    scale = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1) / 127.0
    safe = jnp.where(scale == 0.0, 1.0, scale)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / safe[..., None]), -127, 127)
    return q.astype(jnp.int8), scale.astype(jnp.float32)


def dequantize_kv(q: jnp.ndarray, scale: jnp.ndarray, dtype: jnp.dtype) -> jnp.ndarray:
    """int8 [..., D] × scale [...] → float [..., D].  Left unfused here on
    purpose: XLA folds the convert+multiply into the attention einsum's
    operand, so HBM reads stay int8."""
    return q.astype(dtype) * scale[..., None].astype(dtype)


def update_layer_quantized(
    k_layer: jnp.ndarray,
    v_layer: jnp.ndarray,
    ks_layer: jnp.ndarray,
    vs_layer: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
    offset: jnp.ndarray,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """update_layer for the int8 cache: quantize the new tokens' K/V
    (per-token-per-head absmax) and write values + scales at ``offset``.
    Returns (k_layer, v_layer, ks_layer, vs_layer) updated."""
    kq, ks = quantize_kv(k_new)
    vq, vs = quantize_kv(v_new)
    return (
        _write_at(k_layer, kq, offset),
        _write_at(v_layer, vq, offset),
        _write_at(ks_layer, ks, offset),
        _write_at(vs_layer, vs, offset),
    )
