"""Static preallocated KV cache.

The reference's ``KVCache`` (llama3.2_model.py:303-332) keeps per-layer
Python lists and appends by ``concatenate`` — an O(seq) copy per token per
layer with unbounded growth, and a dynamic shape XLA cannot trace.  The
TPU-native cache is a fixed-size pytree:

    k, v: [num_layers, batch, max_seq, num_kv_heads, head_dim]
    length: int32 scalar — number of tokens written (the reference's
        ``num_items()``, llama3.2_model.py:308-312) — or an int32 [B]
        vector of PER-ROW lengths (batched speculative decoding, where
        each row accepts a different number of draft tokens per round;
        writes become per-row dynamic_update_slices via vmap).

Updates are ``lax.dynamic_update_slice`` at the current offset: O(new
tokens), jit-traceable, donate-able.  The leading layer axis exists so the
model can ``lax.scan`` over layers, carrying each layer's cache slice
through as scan xs/ys.

Sequence-parallel note: the seq axis (2) is placed after batch so a
NamedSharding of P(None, "data", "seq", "model", None) shards cache slots
across chips for long-context decode (BASELINE config 5); head axis (3)
shards under tensor parallelism.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
from jax import lax

from llm_np_cp_tpu.config import ModelConfig


class KVCache(NamedTuple):
    k: jnp.ndarray  # [L, B, S_max, K, D]
    v: jnp.ndarray  # [L, B, S_max, K, D]
    valid: jnp.ndarray  # [B, S_max] bool — written AND not a pad token
    length: jnp.ndarray  # int32 scalar

    @classmethod
    def init(
        cls,
        config: ModelConfig,
        batch_size: int,
        max_seq_len: int,
        dtype: jnp.dtype = jnp.bfloat16,
    ) -> "KVCache":
        shape = (
            config.num_hidden_layers,
            batch_size,
            max_seq_len,
            config.num_key_value_heads,
            config.head_dim,
        )
        return cls(
            k=jnp.zeros(shape, dtype=dtype),
            v=jnp.zeros(shape, dtype=dtype),
            valid=jnp.zeros((batch_size, max_seq_len), dtype=jnp.bool_),
            length=jnp.zeros((), dtype=jnp.int32),
        )

    @property
    def max_seq_len(self) -> int:
        return self.k.shape[2]

    def positions(self) -> jnp.ndarray:
        """Absolute position of every cache slot: [S_max]."""
        return jnp.arange(self.max_seq_len, dtype=jnp.int32)


def truncate(cache: KVCache, new_length: jnp.ndarray) -> KVCache:
    """Logically roll the cache back to ``new_length`` tokens.

    The K/V slabs are left in place — slots ≥ new_length are marked invalid
    in the bitmap and ``length`` moves back, so subsequent writes overwrite
    them and attention (which masks on slot validity + position) never
    reads them.  O(1); the rollback primitive speculative decoding needs
    to discard rejected draft tokens.

    new_length: int32 scalar, or [B] for per-row rollback (each batch row
    keeps a different number of accepted tokens).
    """
    new_length = jnp.asarray(new_length, jnp.int32)
    bound = new_length[:, None] if new_length.ndim == 1 else new_length
    keep = jnp.arange(cache.max_seq_len, dtype=jnp.int32)[None, :] < bound
    return KVCache(
        k=cache.k,
        v=cache.v,
        valid=cache.valid & keep,
        length=new_length,
    )


def update_layer(
    k_layer: jnp.ndarray,
    v_layer: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
    offset: jnp.ndarray,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Write new keys/values at ``offset`` along the seq axis.

    k_layer/v_layer: [B, S_max, K, D]; k_new/v_new: [B, S_new, K, D];
    offset: int32 scalar (tokens already in the cache) or [B] per-row
    offsets (each row writes at its own length — vmapped update, the
    batched-speculative path).  Replaces the reference's per-layer concat
    append (llama3.2_model.py:321-330).

    Overflow contract: if ``offset + S_new > S_max`` the update start is
    silently clamped by ``dynamic_update_slice`` (XLA semantics — no
    data-dependent errors under jit), corrupting slot/position mapping.
    Callers must enforce capacity host-side; ``generate`` does.
    """
    k_new = k_new.astype(k_layer.dtype)
    v_new = v_new.astype(v_layer.dtype)
    zero = jnp.zeros((), dtype=jnp.int32)
    if offset.ndim == 1:
        import jax

        def one(kl, vl, kn, vn, off):
            return (
                lax.dynamic_update_slice(kl, kn, (off, zero, zero)),
                lax.dynamic_update_slice(vl, vn, (off, zero, zero)),
            )

        return jax.vmap(one)(k_layer, v_layer, k_new, v_new, offset)
    k_layer = lax.dynamic_update_slice(k_layer, k_new, (zero, offset, zero, zero))
    v_layer = lax.dynamic_update_slice(v_layer, v_new, (zero, offset, zero, zero))
    return k_layer, v_layer
