"""llm_np_cp_tpu — a TPU-native LLM inference framework.

A brand-new JAX/XLA/Pallas implementation of the capability surface of the
reference `llm_np_cp` repo (from-scratch Llama-3.2 / Gemma-2 autoregressive
inference: HF safetensors loading, RMSNorm / RoPE / GQA attention /
SwiGLU-GeGLU ops, KV-cached prefill+decode, greedy/min-p sampling, streaming
generation) — re-designed TPU-first:

- one jitted decode step with static shapes (no per-token Python math)
- preallocated KV cache updated via ``lax.dynamic_update_slice`` (the
  reference grows its cache by O(n) concatenation each token,
  llama3.2_model.py:321-330 — untraceable under jit)
- ``lax.scan`` over stacked layer params (O(1) compile time in depth)
- tensor/data/sequence parallelism via ``jax.sharding.Mesh`` + NamedSharding
  with XLA collectives over ICI (the reference has no distributed path at
  all, SURVEY §2.9)
- Pallas kernels for the custom-kernel role played by the reference's inline
  CUDA softmax (llama3.2_model.py:924-975)
"""

from llm_np_cp_tpu.config import ModelConfig
from llm_np_cp_tpu.cache import KVCache

__version__ = "0.1.0"

__all__ = ["ModelConfig", "KVCache", "__version__"]
