"""Generation: prefill + decode loops (the reference's L5 layer).

The reference's ``generate`` (llama3.2_model.py:865-902) re-enters Python
every token: re-tokenize → forward → sample → decode → print.  On a TPU —
especially a tunneled one with ~100-300ms dispatch RTT — that loop shape is
the bottleneck regardless of model speed.  Two TPU-native paths replace it:

- **fused** (default): prefill is one jitted call; the whole decode loop is a
  second jitted call — ``lax.scan`` over decode steps with sampling *on
  device*, so N tokens cost one dispatch.  Used by bench.py.
- **streaming**: a Python loop around the jitted single-token step, emitting
  token text as produced (the reference's UX, llama3.2_model.py:899-901) —
  one dispatch per token, with incremental detokenization instead of the
  reference's token→text→token roundtrip (:873-883, which can re-merge
  tokens differently).

Both enforce the KV-cache capacity contract host-side (overflow is silent
under jit — see cache.update_layer) and report the metrics BASELINE.md
tracks: p50-able TTFT and decode tokens/sec.
"""

from __future__ import annotations

import dataclasses
import time
from functools import partial
from typing import Any, Callable, Iterator

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from llm_np_cp_tpu.cache import KVCache, align_capacity
from llm_np_cp_tpu.config import ModelConfig
from llm_np_cp_tpu.models.transformer import forward
from llm_np_cp_tpu.ops.sampling import Sampler

Params = dict[str, Any]


@dataclasses.dataclass
class GenerateResult:
    tokens: np.ndarray  # [B, num_generated]
    ttft_s: float  # time to first token (prefill + first sample)
    decode_tokens_per_s: float  # steady-state decode rate (per sequence)
    num_generated: int
    text: list[str] | None = None
    # decode-loop steps actually EXECUTED (== num_generated-1 for the
    # fixed-trip scan; < that when early_stop exits before the budget).
    # The rate above divides by this, not the budget — an early-stopped
    # batch must not overstate its tok/s (ADVICE r5).
    steps: int = 0


def _check_capacity(prompt_len: int, max_new_tokens: int, max_seq_len: int) -> None:
    need = prompt_len + max_new_tokens
    if need > max_seq_len:
        raise ValueError(
            f"prompt ({prompt_len}) + max_new_tokens ({max_new_tokens}) = "
            f"{need} exceeds KV-cache capacity {max_seq_len}; writes past "
            f"capacity are silently clamped under jit"
        )


# ----------------------------------------------------------------------
# Jitted building blocks
# ----------------------------------------------------------------------

def make_prefill_fn(
    config: ModelConfig, sampler: Sampler, attn_impl: str = "xla"
) -> Callable:
    """(params, prompt_ids, cache, key) → (first_token [B], cache, logits).

    attn_impl="flash" routes prefill attention through the Pallas kernel
    (valid here: prefill always starts from a fresh cache, offset 0);
    "ring" routes it through sequence-parallel ring attention (needs an
    ambient mesh with a "seq" axis — parallel/ring_attention.py).

    The cache argument is DONATED: it is the largest live buffer (layers ×
    batch × max_seq × kv_heads × head_dim) and every call rebinds it, so
    XLA updates the slabs in place instead of allocating a second copy —
    free HBM headroom at bs=32 / long context.  Callers must not reuse the
    input cache object after the call (all in-repo callers rebind).
    """

    @partial(jax.jit, donate_argnums=(2,))
    def prefill(
        params: Params,
        prompt_ids: jnp.ndarray,
        cache: KVCache,
        key: jax.Array,
        attn_mask: jnp.ndarray | None = None,
        pad_offsets: jnp.ndarray | None = None,
    ):
        logits, cache = forward(
            params, prompt_ids, config, cache, logits_last_only=True,
            attn_mask=attn_mask, pad_offsets=pad_offsets,
            attn_impl=attn_impl,
        )
        tok = sampler(key, logits[:, -1])
        return tok, cache, logits[:, -1]

    return prefill


def make_ragged_prefill_step(config: ModelConfig) -> Callable:
    """(params, ids, cache, mask, pads) → (last_logits [B, V], cache) —
    one ragged (left-padded) prefill chunk at the cache's running offset.

    The cache's validity bitmap persists pad slots masked in earlier
    chunks (models/transformer.py), and positions derive from the running
    cache offset minus pad_offsets — so a chunk-sliced attn_mask composes
    exactly with chunking.  The cache is DONATED; callers rebind it.

    Module-level factory so the serving engine (serve/engine.py) compiles
    the SAME program shape the chunked prefill path dispatches.
    """

    @partial(jax.jit, donate_argnums=(2,))
    def ragged_step(
        params: Params, ids: jnp.ndarray, cache: KVCache,
        mask: jnp.ndarray, pads: jnp.ndarray,
    ):
        logits, cache = forward(
            params, ids, config, cache, logits_last_only=True,
            attn_mask=mask, pad_offsets=pads, attn_impl="xla",
        )
        return logits[:, -1], cache

    return ragged_step


def make_chunked_prefill_fn(
    config: ModelConfig,
    sampler: Sampler,
    chunk_size: int,
    attn_impl: str = "xla",
) -> Callable:
    """(params, prompt_ids, cache, key) → (first_token [B], cache, logits)
    — same contract as make_prefill_fn, but the prompt is consumed in
    fixed-width chunks of ``chunk_size`` tokens.

    Each chunk is a cached q_len>1 forward at the cache's running offset
    (the positions-based masks make this exact — the reference mis-masks
    this path, llama3.2_model.py:471-478, so it cannot chunk).  Compile
    cost is O(chunk_size) instead of O(prompt_len): an 8k prompt is
    8 dispatches of ONE compiled 1k-wide program (+ at most one remainder
    shape), not a single monolithic 8k-wide compile — the plausible cause
    of the r2 prefill8k bench timeouts.

    ``attn_impl`` ("flash"/"ring") applies to the FIRST chunk only (those
    kernels read the freshly projected K/V and require a fresh cache —
    models/transformer.py guards this); later chunks attend cached
    history and use the XLA path.
    """
    if chunk_size < 1:
        raise ValueError(f"chunk_size must be >= 1, got {chunk_size}")

    def _make_step(impl: str):
        @partial(jax.jit, donate_argnums=(2,))
        def step(params: Params, ids: jnp.ndarray, cache: KVCache):
            logits, cache = forward(
                params, ids, config, cache, logits_last_only=True,
                attn_impl=impl,
            )
            return logits[:, -1], cache

        return step

    chunk_step = _make_step("xla")
    first_step = chunk_step if attn_impl == "xla" else _make_step(attn_impl)

    # Ragged (left-padded) chunks: a separate jitted step so the dense
    # program keeps its shape (see make_ragged_prefill_step).
    ragged_step = make_ragged_prefill_step(config)

    def prefill_chunked(
        params: Params,
        prompt_ids: jnp.ndarray,
        cache: KVCache,
        key: jax.Array,
        attn_mask: jnp.ndarray | None = None,
        pad_offsets: jnp.ndarray | None = None,
    ):
        ragged = attn_mask is not None or pad_offsets is not None
        if ragged and (attn_mask is None or pad_offsets is None):
            raise ValueError(
                "ragged chunked prefill needs BOTH attn_mask and pad_offsets"
            )
        if ragged and attn_impl != "xla":
            # same contract as the one-shot path: flash/ring masks are
            # slot-index-based and cannot see per-row pads
            raise ValueError(
                f"attn_impl={attn_impl!r} does not support ragged batches; "
                "use attn_impl='xla'"
            )
        s = prompt_ids.shape[1]
        off, step, last = 0, first_step, None
        while off < s:
            w = min(chunk_size, s - off)
            if ragged:
                last, cache = ragged_step(
                    params, prompt_ids[:, off:off + w], cache,
                    attn_mask[:, off:off + w], pad_offsets,
                )
            else:
                last, cache = step(params, prompt_ids[:, off:off + w], cache)
            step, off = chunk_step, off + w
        tok = sampler(key, last)
        return tok, cache, last

    # expose the jitted steps so AOT warmers compile the PROGRAM the
    # measured path dispatches (bench.run_warm; a make_prefill_fn lowered
    # at the chunk shape is a different program and misses the cache)
    prefill_chunked.chunk_step = chunk_step
    prefill_chunked.first_step = first_step
    prefill_chunked.ragged_step = ragged_step
    return prefill_chunked


def _make_sample_tail(
    config: ModelConfig, sampler: Sampler, fused_epilogue: bool
) -> Callable:
    """``(params, key, fwd_out) → next_tok [B]`` — the decode tail.

    fused_epilogue=True: ``fwd_out`` is the pre-final-norm hidden state
    (``forward(..., skip_logits=True)``) and the tail is the ONE Pallas
    ``sample_epilogue`` kernel (norm → lm_head → greedy sample streamed
    over vocab tiles; ``[B, 1, V]`` logits never materialize) via the
    shared ``transformer.sample_epilogue_tail`` invocation.  Callers
    gate on ``transformer.epilogue_gate_error`` (Generator does) — the
    draw is bit-identical to the sampler tail, pinned in tests.
    False: the classic ``sampler(key, logits[:, -1])`` tail/oracle."""
    if not fused_epilogue:
        return lambda params, key, logits: sampler(key, logits[:, -1])
    from llm_np_cp_tpu.models.transformer import sample_epilogue_tail

    def tail(params: Params, key: jax.Array, hid: jnp.ndarray):
        return sample_epilogue_tail(params, hid[:, -1], config)

    return tail


def make_decode_step_fn(
    config: ModelConfig, sampler: Sampler, attn_impl: str = "xla",
    fused_epilogue: bool = False,
) -> Callable:
    """(params, tok [B], cache, key) → (next_tok [B], cache) — one token.
    The cache is donated (updated in place); callers rebind it.
    ``fused_epilogue`` swaps the logits+sampler tail for the fused
    sampling-epilogue kernel (see _make_sample_tail)."""
    sample_tail = _make_sample_tail(config, sampler, fused_epilogue)

    @partial(jax.jit, donate_argnums=(2,))
    def step(params: Params, tok: jnp.ndarray, cache: KVCache, key: jax.Array):
        out, cache = forward(
            params, tok[:, None], config, cache, logits_last_only=True,
            attn_impl=attn_impl, skip_logits=fused_epilogue,
        )
        return sample_tail(params, key, out), cache

    return step


def make_decode_loop_fn(
    config: ModelConfig,
    sampler: Sampler,
    stop_tokens: tuple[int, ...] = (),
    attn_impl: str = "xla",
    early_stop: bool = False,
    fused_epilogue: bool = False,
) -> Callable:
    """(params, first_tok, cache, key, num_steps) →
    (tokens [B, num_steps], cache, steps_executed int32).

    The fused loop: ``lax.scan`` over decode steps entirely on device.
    ``num_steps`` is static (one compile per distinct value).  Sequences
    that hit a stop token keep feeding it (outputs past EOS are repeats the
    caller trims) — branchless, so the scan stays a single fused program.
    attn_impl="flash_decode" routes each step's attention through the
    fused Pallas decode kernel (benchmark-gated; default XLA).

    early_stop=True (requires stop_tokens) swaps the scan for a
    ``lax.while_loop`` that exits once EVERY row is done — a batch whose
    rows all hit EOS early stops paying weight-stream steps for tokens
    nobody will read.  Unfilled tail slots hold 0 and every caller
    normalizes through ``_trim_after_stop``, so outputs are identical to
    the scan path (pinned in tests).  Opt-in: a fixed-trip scan is the
    better program when generation usually runs to the budget.
    """
    stops = jnp.asarray(stop_tokens, dtype=jnp.int32) if stop_tokens else None
    if early_stop and stops is None:
        raise ValueError("early_stop requires stop_tokens")
    sample_tail = _make_sample_tail(config, sampler, fused_epilogue)

    @partial(jax.jit, static_argnums=(4,), donate_argnums=(2,))
    def decode_loop(
        params: Params,
        first_tok: jnp.ndarray,
        cache: KVCache,
        key: jax.Array,
        num_steps: int,
        pad_offsets: jnp.ndarray | None = None,
    ):
        def step(tok, cache, done, k):
            out, cache = forward(
                params, tok[:, None], config, cache, logits_last_only=True,
                pad_offsets=pad_offsets, attn_impl=attn_impl,
                skip_logits=fused_epilogue,
            )
            nxt = sample_tail(params, k, out)
            if stops is not None:
                nxt = jnp.where(done, tok, nxt)
                done = done | jnp.any(nxt[:, None] == stops[None, :], axis=-1)
            return nxt, cache, done

        done0 = (
            jnp.any(first_tok[:, None] == stops[None, :], axis=-1)
            if stops is not None
            else jnp.zeros(first_tok.shape, dtype=jnp.bool_)
        )

        if early_stop:
            b = first_tok.shape[0]
            keys = jax.random.split(key, num_steps)
            buf0 = jnp.zeros((b, num_steps), jnp.int32)

            def cond(state):
                i, _, _, done, _ = state
                return (i < num_steps) & ~jnp.all(done)

            def body(state):
                i, tok, cache, done, buf = state
                nxt, cache, done = step(tok, cache, done, keys[i])
                buf = lax.dynamic_update_slice(buf, nxt[:, None], (0, i))
                return i + 1, nxt, cache, done, buf

            i, _, cache, _, buf = lax.while_loop(
                cond, body, (jnp.zeros((), jnp.int32), first_tok, cache,
                             done0, buf0)
            )
            # i = steps actually EXECUTED (< num_steps when every row hit
            # EOS early); callers compute tok/s from it, not the budget
            return buf, cache, i  # [B, steps]; tail zeros normalized by trim

        keys = jax.random.split(key, num_steps)

        def scan_body(carry, k):
            tok, cache, done = carry
            nxt, cache, done = step(tok, cache, done, k)
            return (nxt, cache, done), nxt

        (_, cache, _), toks = lax.scan(scan_body, (first_tok, cache, done0), keys)
        steps = jnp.asarray(num_steps, jnp.int32)  # fixed-trip: all executed
        return jnp.moveaxis(toks, 0, 1), cache, steps  # [B, steps]

    return decode_loop


# ----------------------------------------------------------------------
# High-level API
# ----------------------------------------------------------------------

class IncrementalDetok:
    """Incremental detokenization: decode the full id list on every push
    and emit only the delta, holding back while the tail may still change
    (mid-UTF-8 merge — avoids the reference's per-step token→text→token
    roundtrip, llama3.2_model.py:873-883).  The ONE held-back rule shared
    by Generator.stream_text and the serving engine's per-request
    streams."""

    def __init__(self, tokenizer: Any) -> None:
        self.tokenizer = tokenizer
        self.ids: list[int] = []
        self.emitted = ""

    def push(self, token_id: int) -> str | None:
        """Append one id; return the newly-stable text delta, if any."""
        self.ids.append(int(token_id))
        text = self.tokenizer.decode(self.ids, skip_special_tokens=True)
        if text.endswith("�"):
            return None
        delta, self.emitted = text[len(self.emitted):], text
        return delta or None

    def flush(self) -> str | None:
        """Emit any held-back tail (call once, after the last push)."""
        text = self.tokenizer.decode(self.ids, skip_special_tokens=True)
        delta = text[len(self.emitted):]
        self.emitted = text
        return delta or None


class Generator:
    """Owns jitted prefill/decode programs for one (model, sampler) pair.

    Compiles lazily per (batch, prompt_len, num_steps) shape; repeated calls
    with the same shapes reuse the compiled programs (jit cache).
    """

    def __init__(
        self,
        params: Params,
        config: ModelConfig,
        *,
        sampler: Sampler | None = None,
        stop_tokens: tuple[int, ...] = (),
        cache_dtype: jnp.dtype = jnp.bfloat16,
        prefill_attn_impl: str = "xla",
        prefill_chunk: int | None = None,
        decode_attn_impl: str = "xla",
        early_stop: bool = False,
    ) -> None:
        self.params = params
        self.config = config
        self.sampler = sampler or Sampler()
        self.stop_tokens = tuple(stop_tokens)
        self.cache_dtype = cache_dtype
        if decode_attn_impl not in ("xla", "flash_decode"):
            # the CLI's user-facing name is "pallas"; catch it (and typos)
            # here instead of silently falling back to the XLA path in
            # run_decoder_layer
            raise ValueError(
                f"decode_attn_impl must be 'xla' or 'flash_decode', "
                f"got {decode_attn_impl!r}"
            )
        # Mosaic gate: a Pallas impl that fails to compile on the live
        # backend downgrades to XLA with one warning instead of dying at
        # first dispatch (ops/pallas/support.py; r3 postmortem).
        from llm_np_cp_tpu.ops.pallas.support import gate_attn_impl

        prefill_attn_impl = gate_attn_impl(prefill_attn_impl)
        decode_attn_impl = gate_attn_impl(
            decode_attn_impl,
            int8_cache=jnp.dtype(cache_dtype) == jnp.int8,
        )
        if prefill_chunk:
            self._prefill = make_chunked_prefill_fn(
                config, self.sampler, prefill_chunk, prefill_attn_impl
            )
        else:
            self._prefill = make_prefill_fn(config, self.sampler, prefill_attn_impl)
        self.last_stream_stats: dict[str, Any] = {}
        # fused sampling epilogue (tick-tail fusion, the serve engine's
        # gate shared verbatim via transformer.epilogue_gate_error):
        # greedy sampler + float/int8-"q" head + probe pass → the
        # decode tail runs norm→lm_head→sample as one Pallas kernel and
        # the [B, 1, V] logits never materialize; anything else keeps
        # the logits+Sampler tail (the oracle)
        from llm_np_cp_tpu.models.transformer import epilogue_gate_error

        self.epilogue_impl = (
            "fused"
            if epilogue_gate_error(params, config, self.sampler.kind)
            is None else "xla"
        )
        fused_epi = self.epilogue_impl == "fused"
        self._step = make_decode_step_fn(
            config, self.sampler, decode_attn_impl,
            fused_epilogue=fused_epi,
        )
        self._loop = make_decode_loop_fn(
            config, self.sampler, self.stop_tokens, decode_attn_impl,
            early_stop=early_stop, fused_epilogue=fused_epi,
        )

    def _init_cache(self, batch: int, max_seq_len: int) -> KVCache:
        # Capacity is rounded UP to a multiple of 128: slots past the
        # requested length are masked off (validity masks use per-row
        # lengths, not capacity), decode_attention's kv-block search never
        # collapses toward block_s=1 on a prime capacity, and seq-axis
        # sharding divisibility is automatic.  Contract documented in
        # cache.py.
        return KVCache.init(
            self.config, batch, align_capacity(max_seq_len),
            dtype=self.cache_dtype,
        )

    def _run_fused(
        self,
        prompt_ids: jnp.ndarray,
        max_new_tokens: int,
        max_seq_len: int | None,
        seed: int,
        attn_mask: jnp.ndarray | None = None,
        pad_offsets: jnp.ndarray | None = None,
    ) -> GenerateResult:
        """Shared fused runner: prefill dispatch + decode-scan dispatch."""
        b, s = prompt_ids.shape
        max_seq_len = max_seq_len or s + max_new_tokens
        _check_capacity(s, max_new_tokens, max_seq_len)

        key = jax.random.PRNGKey(seed)
        k_pre, k_loop = jax.random.split(key)
        cache = self._init_cache(b, max_seq_len)

        t0 = time.perf_counter()
        tok0, cache, _ = self._prefill(
            self.params, prompt_ids, cache, k_pre, attn_mask, pad_offsets
        )
        tok0.block_until_ready()
        t1 = time.perf_counter()

        if max_new_tokens > 1:
            rest, cache, steps_dev = self._loop(
                self.params, tok0, cache, k_loop, max_new_tokens - 1, pad_offsets
            )
            rest.block_until_ready()
            t2 = time.perf_counter()
            tokens = np.concatenate([np.asarray(tok0)[:, None], np.asarray(rest)], axis=1)
            # rate over steps actually EXECUTED: under early_stop the
            # while_loop may exit before the budget, and dividing the
            # budget by the (shorter) loop time overstated tok/s
            steps = int(np.asarray(steps_dev))
            rate = steps / (t2 - t1) if steps > 0 else float("nan")
        else:
            tokens = np.asarray(tok0)[:, None]
            rate = float("nan")
            steps = 0

        tokens = _trim_after_stop(tokens, self.stop_tokens)
        return GenerateResult(
            tokens=tokens,
            ttft_s=t1 - t0,
            decode_tokens_per_s=rate,
            num_generated=tokens.shape[1],
            steps=steps,
        )

    # -- fused ---------------------------------------------------------
    def generate(
        self,
        prompt_ids: np.ndarray | jnp.ndarray,
        max_new_tokens: int,
        *,
        max_seq_len: int | None = None,
        seed: int = 0,
    ) -> GenerateResult:
        """Fused generation: 2 device dispatches total (prefill, decode scan)."""
        prompt_ids = jnp.asarray(prompt_ids, dtype=jnp.int32)
        if prompt_ids.ndim == 1:
            prompt_ids = prompt_ids[None, :]
        return self._run_fused(prompt_ids, max_new_tokens, max_seq_len, seed)

    # -- ragged batch --------------------------------------------------
    @staticmethod
    def left_pad(
        prompts: list[np.ndarray | list[int]],
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """The ragged left-pad contract, in ONE place: prompts → (ids
        [B, S] zero-left-padded, mask [B, S] valid, pads [B] per-row pad
        counts).  Used by Generator.generate_ragged and
        SpeculativeGenerator.generate_ragged."""
        arrs = [np.asarray(p, dtype=np.int32).reshape(-1) for p in prompts]
        if not arrs:
            raise ValueError("left_pad needs at least one prompt")
        empty = [i for i, a in enumerate(arrs) if a.size == 0]
        if empty:
            # an all-pad row would sample its first token from a fully
            # masked attention — fail fast instead of emitting garbage
            raise ValueError(f"empty prompt at index {empty[0]}")
        s = max(a.size for a in arrs)
        b = len(arrs)
        ids = np.zeros((b, s), dtype=np.int32)
        mask = np.zeros((b, s), dtype=bool)
        pads = np.zeros(b, dtype=np.int32)
        for i, a in enumerate(arrs):
            pads[i] = s - a.size
            ids[i, pads[i]:] = a
            mask[i, pads[i]:] = True
        return ids, mask, pads

    def generate_ragged(
        self,
        prompts: list[np.ndarray | list[int]],
        max_new_tokens: int,
        *,
        max_seq_len: int | None = None,
        seed: int = 0,
    ) -> GenerateResult:
        """Batch generation over prompts of different lengths.

        Prompts are LEFT-padded to a common length; per-row ``pad_offsets``
        keep RoPE positions and causal masks exact (each row behaves as if
        it ran alone — verified in tests), and the pad slots are marked
        invalid in the cache bitmap.  The reference has no batching at all
        (its generate loop is bs=1, llama3.2_model.py:865-902).
        """
        ids, mask, pads = self.left_pad(prompts)
        return self._run_fused(
            jnp.asarray(ids),
            max_new_tokens,
            max_seq_len,
            seed,
            attn_mask=jnp.asarray(mask),
            pad_offsets=jnp.asarray(pads),
        )

    def generate_many(
        self,
        prompts: list[np.ndarray | list[int]],
        max_new_tokens: int,
        *,
        batch_size: int = 8,
        max_seq_len: int | None = None,
        seed: int = 0,
    ) -> list[GenerateResult]:
        """Dynamic batching over a workload of any size: prompts are
        grouped (longest-first, so rows in a batch have similar lengths
        and waste little pad) into ragged batches of ``batch_size`` and
        each batch runs the fused path; returns one GenerateResult PER
        PROMPT (a single-row tokens array), in the caller's original
        prompt order, each carrying its own batch's ttft/rate.

        With ``early_stop`` on the Generator, a batch whose rows all hit
        EOS early releases the chip to the next batch — throughput-
        oriented offline serving without a resident server.  (The
        reference processes one prompt at a time, llama3.2_model.py:865.)
        """
        if batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        order = sorted(
            range(len(prompts)), key=lambda i: -len(np.asarray(prompts[i]).reshape(-1))
        )
        results: list[GenerateResult | None] = [None] * len(prompts)
        for start in range(0, len(order), batch_size):
            idx = order[start:start + batch_size]
            res = self.generate_ragged(
                [prompts[i] for i in idx], max_new_tokens,
                max_seq_len=max_seq_len, seed=seed + start,
            )
            for row, i in enumerate(idx):
                results[i] = GenerateResult(
                    tokens=res.tokens[row:row + 1],
                    ttft_s=res.ttft_s,
                    decode_tokens_per_s=res.decode_tokens_per_s,
                    num_generated=res.num_generated,
                    steps=res.steps,
                )
        return results  # type: ignore[return-value]

    # -- streaming -----------------------------------------------------
    def stream(
        self,
        prompt_ids: np.ndarray | jnp.ndarray,
        max_new_tokens: int,
        *,
        max_seq_len: int | None = None,
        seed: int = 0,
    ) -> Iterator[int]:
        """Yield token ids one at a time (batch size 1)."""
        prompt_ids = jnp.asarray(prompt_ids, dtype=jnp.int32)
        if prompt_ids.ndim == 1:
            prompt_ids = prompt_ids[None, :]
        if prompt_ids.shape[0] != 1:
            raise ValueError("streaming supports batch size 1")
        s = prompt_ids.shape[1]
        max_seq_len = max_seq_len or s + max_new_tokens
        _check_capacity(s, max_new_tokens, max_seq_len)

        key = jax.random.PRNGKey(seed)
        cache = self._init_cache(1, max_seq_len)
        key, k = jax.random.split(key)
        tok, cache, _ = self._prefill(self.params, prompt_ids, cache, k)
        t = int(tok[0])
        yield t
        for _ in range(max_new_tokens - 1):
            if t in self.stop_tokens:
                return
            key, k = jax.random.split(key)
            tok, cache = self._step(self.params, tok, cache, k)
            t = int(tok[0])
            yield t

    def stream_text(
        self,
        tokenizer: Any,
        prompt: str,
        max_new_tokens: int,
        *,
        seed: int = 0,
        echo: Callable[[str], None] | None = None,
    ) -> str:
        """Streaming text generation with incremental detokenization.

        Emits only the *delta* between successive decodes of the generated
        ids — avoids the reference's per-step token→text→token roundtrip
        (llama3.2_model.py:873-883) while handling multi-byte merges.
        """
        prompt_ids = tokenizer(prompt, return_tensors="np")["input_ids"][0]
        detok = IncrementalDetok(tokenizer)
        t0 = time.perf_counter()
        ttft = None
        for t in self.stream(prompt_ids, max_new_tokens, seed=seed):
            if ttft is None:
                ttft = time.perf_counter() - t0
            delta = detok.push(t)
            if echo and delta:
                echo(delta)
        tail = detok.flush()
        if echo and tail:
            echo(tail)
        self.last_stream_stats = {
            "tokens": len(detok.ids),
            "ttft_s": ttft,
            "duration_s": time.perf_counter() - t0,
        }
        return detok.emitted


def _trim_after_stop(tokens: np.ndarray, stop_tokens: tuple[int, ...]) -> np.ndarray:
    """Replace everything after the first stop token with that stop token
    (fused decode keeps generating repeats past EOS by construction)."""
    if not stop_tokens:
        return tokens
    out = tokens.copy()
    for b in range(out.shape[0]):
        hits = np.isin(out[b], stop_tokens).nonzero()[0]
        if hits.size:
            out[b, hits[0]:] = out[b, hits[0]]
    return out
