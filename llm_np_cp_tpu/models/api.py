"""Reference-shaped model API.

The reference's model objects are called as functions returning the 5-tuple
``(loss, logits, kv_cache, hidden_states, attentions)``
(llama3.2_model.py:816-822) with HF-style accessor methods
(:744-766).  ``CausalLM`` reproduces that calling convention on top of the
functional core — a migration surface for reference users; new code should
call ``models.transformer.forward`` directly.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp

from llm_np_cp_tpu.cache import KVCache
from llm_np_cp_tpu.config import ModelConfig
from llm_np_cp_tpu.models.transformer import forward


class CausalLM:
    """Callable model facade over (params, config)."""

    def __init__(self, params: dict[str, Any], config: ModelConfig) -> None:
        self.params = params
        self.config = config

    def __call__(
        self,
        input_ids: jnp.ndarray,
        use_cache: bool = False,
        kv_cache: KVCache | None = None,
        labels: jnp.ndarray | None = None,
        output_hidden_states: bool = False,
        output_attentions: bool = False,
    ):
        """Returns ``(loss, logits, kv_cache, hidden_states, attentions)``.

        loss is None unless ``labels`` is given (the reference's loss slot
        is ALWAYS None, llama3.2_model.py:809 — we fill it when asked).
        """
        cache = kv_cache if use_cache else None
        out = forward(
            self.params,
            input_ids,
            self.config,
            cache,
            output_hidden_states=output_hidden_states,
            output_attentions=output_attentions,
        )
        logits, new_cache = out[0], out[1]
        aux = out[2] if len(out) > 2 else {}
        loss = None
        if labels is not None:
            # HF convention: labels align with input_ids, shift happens here;
            # positions labeled -100 are ignored.
            import jax

            logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
            tgt = labels[:, 1:]
            nll = -jnp.take_along_axis(
                logp, jnp.maximum(tgt, 0)[..., None], axis=-1
            )[..., 0]
            mask = (tgt != -100).astype(jnp.float32)
            loss = jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
        return (
            loss,
            logits,
            new_cache,
            aux.get("hidden_states"),
            aux.get("attentions"),
        )

    # HF-style accessors (reference parity, llama3.2_model.py:744-766)
    def get_input_embeddings(self) -> jnp.ndarray:
        return self.params["embed_tokens"]

    def set_input_embeddings(self, value: jnp.ndarray) -> None:
        self.params["embed_tokens"] = value

    def get_output_embeddings(self) -> jnp.ndarray:
        if self.config.tie_word_embeddings:
            return self.params["embed_tokens"]
        return self.params["lm_head"]

    def set_output_embeddings(self, value: jnp.ndarray) -> None:
        """llama3.2_model.py:757-758; a tied model's head IS the embedding
        table, so setting one sets both (the reference, which materializes
        the tied head as a second attribute, silently un-ties here)."""
        if self.config.tie_word_embeddings:
            self.params["embed_tokens"] = value
        else:
            self.params["lm_head"] = value

    def get_decoder(self) -> dict[str, Any]:
        """The backbone params (everything but the head) — the functional
        analogue of the reference's ``self.model`` (llama3.2_model.py:765-766)."""
        return {k: v for k, v in self.params.items() if k != "lm_head"}

    def set_decoder(self, decoder: dict[str, Any]) -> None:
        """llama3.2_model.py:761-762: swap the backbone, keep the head."""
        head = self.params.get("lm_head")
        self.params = dict(decoder)
        if head is not None and "lm_head" not in self.params:
            self.params["lm_head"] = head
