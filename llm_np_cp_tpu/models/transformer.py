"""Generic decoder-only transformer forward pass.

Covers the reference's two families with one traced function:
- Llama-3.2: pre-norm residual blocks, SwiGLU MLP, tied lm_head
  (llama3.2_model.py:511-822)
- Gemma-2: sandwich norms (4/layer, post-norms inside the residual,
  gemma2_model.py:588-643), embedding scaling (:738-739), GeGLU, attention
  and final-logit softcapping, alternating sliding/global attention —
  including the two features the reference dropped (SURVEY §2.7).

Architecture (TPU-first, not a translation):
- params are a dict pytree; per-layer weights are stacked on a leading
  ``[num_layers, ...]`` axis and the layer loop is ``lax.scan`` — compile
  time is O(1) in depth and XLA double-buffers the per-layer weight fetch
  from HBM (the reference re-dispatches Python per layer,
  llama3.2_model.py:685-697).
- projection weights are stored **(in, out)** so every matmul is
  ``x @ W`` with f32 accumulation on the MXU (HF checkpoints store
  [out, in]; the loader transposes once at load time).
- activations keep layout [B, S, H*D] / [B, S, K, D]: sequence second,
  head_dim last — KV-cache writes are contiguous and the lane dim is the
  128-wide axis.
- masks derive from positions, never from shape branches (the reference's
  ``q_len > 2`` mask guard, llama3.2_model.py:471, is a bug we don't copy).
"""

from __future__ import annotations

import math
import os
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from llm_np_cp_tpu.cache import (
    KVCache,
    dequantize_kv,
    update_layer,
    update_layer_quantized,
)
from llm_np_cp_tpu.config import ModelConfig
from llm_np_cp_tpu.ops.activations import ACT2FN, softcap
from llm_np_cp_tpu.ops.attention import causal_mask, gqa_attention
from llm_np_cp_tpu.ops.norms import rms_norm
from llm_np_cp_tpu.ops.rope import apply_rope, rope_cos_sin
from llm_np_cp_tpu.quant import quant_einsum

Params = dict[str, Any]


# ----------------------------------------------------------------------
# Parameter pytree
# ----------------------------------------------------------------------

def param_shapes(config: ModelConfig) -> dict[str, Any]:
    """Shape/dtype-free spec of the parameter pytree (stacked layers)."""
    L = config.num_hidden_layers
    H = config.hidden_size
    D = config.head_dim
    NH = config.num_attention_heads
    NK = config.num_key_value_heads
    I = config.intermediate_size
    V = config.vocab_size
    layers: dict[str, tuple[int, ...]] = {
        "ln_attn_in": (L, H),
        "q_proj": (L, H, NH * D),
        "k_proj": (L, H, NK * D),
        "v_proj": (L, H, NK * D),
        "o_proj": (L, NH * D, H),
        "ln_mlp_in": (L, H),
    }
    if config.attention_bias:
        # HF Llama-family attention_bias puts a bias on all four attention
        # projections; Qwen-2 biases only Q/K/V (attention_out_bias=False)
        layers.update(
            q_bias=(L, NH * D), k_bias=(L, NK * D), v_bias=(L, NK * D),
        )
    if config.o_proj_bias:
        # independent gate: o_proj_bias defaults to attention_bias but an
        # explicit attention_out_bias=True stands alone too
        layers.update(o_bias=(L, H))
    if config.mlp_bias:
        if config.is_moe:
            raise NotImplementedError("mlp_bias is not supported for MoE configs")
        layers.update(gate_bias=(L, I), up_bias=(L, I), down_bias=(L, H))
    if config.is_moe:
        E = config.num_local_experts
        layers.update(
            router=(L, H, E),
            gate_proj=(L, E, H, I),
            up_proj=(L, E, H, I),
            down_proj=(L, E, I, H),
        )
    else:
        layers.update(
            gate_proj=(L, H, I),
            up_proj=(L, H, I),
            down_proj=(L, I, H),
        )
    if config.sandwich_norms:
        layers["ln_attn_out"] = (L, H)
        layers["ln_mlp_out"] = (L, H)
    spec: dict[str, Any] = {
        "embed_tokens": (V, H),
        "layers": layers,
        "final_norm": (H,),
    }
    if not config.tie_word_embeddings:
        spec["lm_head"] = (H, V)
    return spec


# jitted init program per (config, dtype) — see init_params
_INIT_PROGRAMS: dict = {}


def init_params(
    rng: jax.Array, config: ModelConfig, dtype: jnp.dtype = jnp.bfloat16
) -> Params:
    """Random small-scale init (for tests and synthetic benchmarks).

    The whole init runs as ONE jitted program: eager per-leaf
    ``jax.random.normal`` costs a device dispatch per leaf plus an f32
    intermediate materialization each — at 3B scale over a tunneled chip
    that is minutes of round-trips (the r1–r4 benches never got a 3B
    number; the breadcrumbs pointed at params build).  Under jit the init
    is a single dispatch and every leaf materializes on-device in its
    final dtype.
    """
    spec = param_shapes(config)
    paths_leaves, treedef = jax.tree_util.tree_flatten_with_path(
        spec, is_leaf=lambda x: isinstance(x, tuple)
    )
    # cache the jitted program per (config, dtype) — a fresh closure per
    # call would re-trace and recompile the identical init every time
    # (the test suite calls init_params hundreds of times)
    cache_key = (config, jnp.dtype(dtype).name)
    _init = _INIT_PROGRAMS.get(cache_key)
    if _init is None:

        @jax.jit
        def _init(rng: jax.Array) -> list[jnp.ndarray]:
            keys = jax.random.split(rng, len(paths_leaves))

            def make(key: jax.Array, path: tuple, shape: tuple[int, ...]) -> jnp.ndarray:
                name = path[-1].key  # leaf name in the dict pytree
                if name.startswith("ln_") or name == "final_norm":
                    # norm gammas: zeros under unit-offset (so 1+w == 1), ones otherwise
                    init = 0.0 if config.rms_norm_unit_offset else 1.0
                    return jnp.full(shape, init, dtype=dtype)
                if name.endswith("_bias"):
                    # biases start small-but-nonzero so tests exercise the add path
                    return (jax.random.normal(key, shape, jnp.float32) * 0.02).astype(dtype)
                scale = 0.02
                return (jax.random.normal(key, shape, dtype=jnp.float32) * scale).astype(dtype)

            return [make(k, p, s) for k, (p, s) in zip(keys, paths_leaves)]

        _INIT_PROGRAMS[cache_key] = _init

    return jax.tree.unflatten(treedef, _init(rng))


# ----------------------------------------------------------------------
# Forward
# ----------------------------------------------------------------------

def compute_dtype(params: Params) -> jnp.dtype:
    """Activation dtype: the norm gammas' dtype (always a float leaf, even
    when the matmul weights are int8-quantized — quant.py)."""
    return params["final_norm"].dtype


def scan_unroll(config: ModelConfig) -> int:
    """Layer-scan unroll factor so the compiler can software-pipeline the
    per-layer weight stream across layer boundaries — decode is bound by
    that stream.  config.scan_unroll is the API (part of every jit cache
    key the config closes over); LLMTPU_SCAN_UNROLL overrides it at TRACE
    time only — an env change after a fn's first trace does nothing for
    that fn (the bench A/Bs via the env var in fresh subprocesses).
    Non-divisors and malformed values degrade to 1.  The ONE definition
    shared by ``forward`` and the serve engine's paged decode scan."""
    try:
        unroll = int(
            os.environ.get("LLMTPU_SCAN_UNROLL", str(config.scan_unroll)).strip()
        )
    except ValueError:
        unroll = 1  # malformed values degrade like non-divisors do
    if unroll < 1 or config.num_hidden_layers % unroll:
        unroll = 1
    return unroll


def _project(x: jnp.ndarray, w: Any) -> jnp.ndarray:
    return quant_einsum("bsh,ho->bso", x, w).astype(x.dtype)


def embed_inputs(params: Params, input_ids: jnp.ndarray, config: ModelConfig) -> jnp.ndarray:
    """Token embedding lookup (+ Gemma's sqrt(hidden) scaling,
    gemma2_model.py:738-739, applied in the weight dtype to match the
    reference's bf16 rounding)."""
    dtype = compute_dtype(params)
    emb = params["embed_tokens"]
    if isinstance(emb, dict):  # int8 rows with per-row scales
        x = (emb["q"][input_ids].astype(jnp.float32) * emb["s"][input_ids]).astype(dtype)
    else:
        x = emb[input_ids].astype(dtype)
    if config.scale_embeddings:
        normalizer = jnp.array(math.sqrt(config.hidden_size), dtype=dtype)
        x = x * normalizer
    return x


def final_logits(
    params: Params, x: jnp.ndarray, config: ModelConfig, *, last_only: bool = False
) -> jnp.ndarray:
    """Final RMSNorm → (tied) lm_head → optional softcap → float32 logits."""
    x = rms_norm(
        x, params["final_norm"], eps=config.rms_norm_eps,
        unit_offset=config.rms_norm_unit_offset,
    )
    if last_only:
        x = x[:, -1:, :]
    if config.tie_word_embeddings:
        logits = quant_einsum("bsh,vh->bsv", x, params["embed_tokens"])
    else:
        logits = quant_einsum("bsh,hv->bsv", x, params["lm_head"])
    if config.final_logit_softcapping is not None:
        logits = softcap(logits, config.final_logit_softcapping)
    return logits.astype(jnp.float32)


def head_quant_mode(params: Params, config: ModelConfig) -> str | None:
    """How the lm-head weight is stored: ``"float"`` (plain array),
    ``"int8"`` (quant.py ``"q"`` payload — the fused sampling epilogue's
    int8 kernel streams it), or ``None`` for payloads the epilogue
    kernel does not cover (``q4``/``qa`` — those keep the XLA tail).
    The ONE classification shared by the serve engine's epilogue gate
    and the offline Generator, so the two cannot drift."""
    w = (params.get("embed_tokens") if config.tie_word_embeddings
         else params.get("lm_head"))
    if w is None:
        return None
    if isinstance(w, dict):
        return "int8" if "q" in w and "s" in w else None
    return "float"


def epilogue_params(
    params: Params, config: ModelConfig
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray | None]:
    """``(final-norm gamma, lm-head weight payload, [1, V] f32 scales
    or None)`` — the leaves the fused sampling epilogue kernel streams
    (ops/pallas/sample_epilogue.py).  Tied heads hand over the
    embedding table ``[V, H]`` (per-row scales reshaped to the kernel's
    per-column layout), untied heads ``[H, V]``.  Callers gate on
    ``head_quant_mode`` first — this raises on unsupported payloads."""
    w = (params["embed_tokens"] if config.tie_word_embeddings
         else params["lm_head"])
    if isinstance(w, dict):
        return params["final_norm"], w["q"], w["s"].reshape(1, -1)
    return params["final_norm"], w, None


def sample_epilogue_tail(
    params: Params, x: jnp.ndarray, config: ModelConfig
) -> jnp.ndarray:
    """Greedy-sample rows of PRE-final-norm hidden states ``x [N, H]``
    through the fused sampling epilogue kernel → ``[N]`` int32 token
    ids.  The ONE invocation shared by the serve engine's three step
    builders and the offline Generator's decode tail, so the kernel
    kwargs (norm eps/offset, softcap, head layout+scales) cannot drift
    between paths — a new config knob lands here once or nowhere."""
    from llm_np_cp_tpu.ops.pallas.sample_epilogue import sample_epilogue

    gamma, w, w_scale = epilogue_params(params, config)
    return sample_epilogue(
        x, gamma, w, w_scale=w_scale,
        tied=config.tie_word_embeddings,
        eps=config.rms_norm_eps,
        unit_offset=config.rms_norm_unit_offset,
        logit_softcap=config.final_logit_softcapping,
    )


def epilogue_gate_error(
    params: Params, config: ModelConfig, sampler_kind: str
) -> str | None:
    """None when the fused sampling epilogue reproduces this
    (params, sampler) pair's draw bit-identically and the kernel is
    available, else the reason it cannot — the ONE gate shared by
    ``ServeEngine`` and the offline ``Generator`` (callers add their
    own topology constraints, e.g. the engine's unsharded-mesh check,
    on top)."""
    if sampler_kind != "greedy":
        return (f"sampler kind {sampler_kind!r} (only the greedy draw "
                "is bit-identical to the streamed argmax)")
    hq = head_quant_mode(params, config)
    if hq is None:
        return "unsupported lm-head payload (q4/qa heads keep the XLA tail)"
    from llm_np_cp_tpu.ops.pallas.support import (
        epilogue_kernel_name,
        kernel_error,
    )

    return kernel_error(epilogue_kernel_name(hq == "int8"))


def run_decoder_layer(
    w: Params,
    x: jnp.ndarray,
    *,
    config: ModelConfig,
    act: Any,
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    mask_global: jnp.ndarray | None = None,
    mask_local: jnp.ndarray | None = None,
    sliding: jnp.ndarray | bool = False,
    attn_impl: str = "xla",
    kv_update: Any = None,
    output_attentions: bool = False,
    attn_fn: Any = None,
) -> tuple[
    jnp.ndarray,
    tuple[jnp.ndarray, jnp.ndarray],
    jnp.ndarray | None,
    jnp.ndarray,
]:
    """One decoder block (pre-norm or Gemma sandwich-norm residual).

    w: one layer's weight dict (un-stacked leaves).
    kv_update: optional ``(k, v) -> (k_att, v_att)`` hook — the cache write;
        when None, attention runs over the freshly projected K/V (the
        reference's cache-less mode, llama3.2_model.py:874-880).
    sliding: traced bool — selects ``mask_local`` (and the flash kernel's
        window) for Gemma-2's alternating local layers.
    attn_fn: optional ``(q, k_att, v_att, sliding) -> attn`` override — the
        serving engine's paged decode path supplies the block-table-native
        kernel here (its visibility comes from per-row scalars, not a
        [B, Sq, Skv] mask, so ``mask_global``/``mask_local`` may be None).

    Returns ``(x_out, (k_att, v_att), attn_weights | None, moe_aux_loss)``
    (aux loss is 0.0 for dense layers).  Shared by ``forward``'s lax.scan,
    the pipeline-parallel schedule (parallel/pipeline.py), and the serve
    engine's paged decode scan, so all trace identical layer math.
    """
    if attn_fn is None:
        mask = (
            jnp.where(sliding, mask_local, mask_global)
            if config.sliding_window is not None
            else mask_global
        )
    b, s = x.shape[:2]
    h = rms_norm(
        x, w["ln_attn_in"], eps=config.rms_norm_eps,
        unit_offset=config.rms_norm_unit_offset,
    )
    def _proj_b(x, wname):
        y = _project(x, w[wname])
        bias = w.get(wname.replace("_proj", "_bias"))
        return y + bias.astype(y.dtype) if bias is not None else y

    q = _proj_b(h, "q_proj").reshape(b, s, config.num_attention_heads, config.head_dim)
    k = _proj_b(h, "k_proj").reshape(b, s, config.num_key_value_heads, config.head_dim)
    v = _proj_b(h, "v_proj").reshape(b, s, config.num_key_value_heads, config.head_dim)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)

    if kv_update is not None:
        k_att, v_att = kv_update(k, v)
    else:
        k_att, v_att = k, v

    attn_weights = None
    if attn_fn is not None:
        attn = attn_fn(q, k_att, v_att, sliding)
    elif attn_impl in ("flash", "ring"):
        if attn_impl == "flash":
            from llm_np_cp_tpu.ops.pallas.flash_attention import flash_attention as _impl_fn
        else:
            from llm_np_cp_tpu.parallel.ring_attention import ring_attention_ctx as _impl_fn

        def _fresh_attn(window):
            return _impl_fn(
                q, k, v,  # current K/V: self-attention over 0..S-1
                scale=config.attn_scale,
                logit_softcap=config.attn_logit_softcapping,
                window=window,
            )

        if config.sliding_window is not None:
            attn = lax.cond(
                sliding,
                lambda: _fresh_attn(config.sliding_window),
                lambda: _fresh_attn(None),
            )
        else:
            attn = _fresh_attn(None)
    elif attn_impl == "flash_decode" and s == 1:
        # Fused single-token attention over the cache slab; consumes the
        # same mask as the XLA path (validity ∧ window ∧ ragged pads), so
        # every decode feature works unchanged.  Prefill/chunked calls
        # (s > 1) under this impl fall through to the XLA path below.
        # An int8 cache arrives as (values, scales) tuples: the kernel
        # streams 1-byte slabs and dequantizes in VMEM.
        from llm_np_cp_tpu.ops.pallas.decode_attention import decode_attention

        if isinstance(k_att, tuple):
            (k_vals, k_sc), (v_vals, v_sc) = k_att, v_att
        else:
            k_vals, k_sc, v_vals, v_sc = k_att, None, v_att, None
        attn = decode_attention(
            q, k_vals, v_vals,
            jnp.broadcast_to(mask, (b, 1, k_vals.shape[1]))[:, 0],
            k_scale=k_sc, v_scale=v_sc,
            scale=config.attn_scale,
            logit_softcap=config.attn_logit_softcapping,
        )
    else:
        attn = gqa_attention(
            q, k_att, v_att, mask,
            scale=config.attn_scale,
            logit_softcap=config.attn_logit_softcapping,
            return_weights=output_attentions,
        )
        if output_attentions:
            attn, attn_weights = attn
    attn = _project(attn.reshape(b, s, -1), w["o_proj"])
    if "o_bias" in w:
        attn = attn + w["o_bias"].astype(attn.dtype)
    if config.sandwich_norms:
        attn = rms_norm(
            attn, w["ln_attn_out"], eps=config.rms_norm_eps,
            unit_offset=config.rms_norm_unit_offset,
        )
    x = x + attn

    h = rms_norm(
        x, w["ln_mlp_in"], eps=config.rms_norm_eps,
        unit_offset=config.rms_norm_unit_offset,
    )
    moe_aux = jnp.zeros((), jnp.float32)
    if config.is_moe:
        from llm_np_cp_tpu.ops.moe import moe_mlp

        mlp, moe_aux = moe_mlp(
            h, w["router"], w["gate_proj"], w["up_proj"], w["down_proj"],
            act=act, top_k=config.num_experts_per_tok,
            capacity_factor=config.moe_capacity_factor,
            group_size=config.moe_group_size,
        )
    else:
        gate = act(_proj_b(h, "gate_proj"))
        up = _proj_b(h, "up_proj")
        mlp = _proj_b(gate * up, "down_proj")
    if config.sandwich_norms:
        mlp = rms_norm(
            mlp, w["ln_mlp_out"], eps=config.rms_norm_eps,
            unit_offset=config.rms_norm_unit_offset,
        )
    x = x + mlp
    return x, (k_att, v_att), attn_weights, moe_aux


def forward(
    params: Params,
    input_ids: jnp.ndarray,
    config: ModelConfig,
    cache: KVCache | None = None,
    *,
    positions: jnp.ndarray | None = None,
    attn_mask: jnp.ndarray | None = None,
    pad_offsets: jnp.ndarray | None = None,
    logits_last_only: bool = False,
    output_hidden_states: bool = False,
    output_attentions: bool = False,
    output_router_losses: bool = False,
    attn_impl: str = "xla",
    skip_logits: bool = False,
) -> tuple:
    """Run the decoder.

    skip_logits=True returns the PRE-final-norm hidden states in the
    logits slot ([B, S, H], or [B, 1, H] under logits_last_only)
    instead of running ``final_logits`` — the fused sampling epilogue
    (ops/pallas/sample_epilogue.py) consumes them and computes
    norm→lm_head→sample in one kernel, so the ``[B, S, V]`` logits
    never materialize.  Callers own the epilogue; everything else about
    the forward (cache writes, masks, aux outputs) is unchanged.

    input_ids: [B, S] int32.
    cache: static KVCache, or None for the reference's cache-less
        full-recompute mode (llama3.2_model.py:874-880).
    positions: [B, S] absolute positions; defaults to
        ``cache.length + arange(S)`` (cache-aware positions, the reference's
        llama3.2_model.py:651-664).
    attn_mask: optional [B, S] bool marking valid (non-pad) input tokens.
    pad_offsets: optional [B] int32 — per-row LEFT-padding amounts for
        ragged batches.  Row b's token in cache slot j carries absolute
        position ``j - pad_offsets[b]``; RoPE and causal masks become
        row-aware, so sequences of different lengths batch together with
        correct relative positions (combine with attn_mask marking the pad
        slots invalid).  The reference can't batch at all (bs=1 generate
        loop, SURVEY §2.8).
    logits_last_only: compute lm_head for the final position only — the
        reference computes logits for ALL positions then samples from the
        last (llama3.2_model.py:803, :891), an O(S·V) waste in prefill.
    output_hidden_states / output_attentions: collect per-layer inputs
        ([L, B, S, H]) / attention probabilities ([L, B, H, Sq, Skv]) as
        scan outputs.  The reference accumulates these tuples on EVERY
        forward (llama3.2_model.py:623-624, 679-706) — a memory tax; here
        they are opt-in (SURVEY §2.6 quirks).  output_attentions requires
        the XLA attention path (the flash kernel never materializes them).
    attn_impl: "xla" (default), "flash" (the Pallas blockwise kernel), or
        "ring" (sequence-parallel ring attention over the ambient mesh's
        "seq" axis — parallel/ring_attention.py; replaces the reference's
        single-device full [S,S] score matrix, llama3.2_model.py:467-469).
        Both are valid only for self-attention over positions 0..S-1
        (fresh-cache prefill or cache-less forward with no padding); the
        cache is still written, but attention reads the current K/V
        directly (identical by causality since later slots are masked).
        "flash_decode" fuses the single-token decode step over the cache
        slab (ops/pallas/decode_attention.py); it consumes the standard
        mask, so it composes with caches, ragged batches, and sliding
        windows, and falls back to XLA for q_len > 1.

    Returns (logits, new_cache) — logits [B, S, V] float32 (or [B, 1, V]
    when logits_last_only) — plus an aux dict with "hidden_states" /
    "attentions" when either output flag is set.
    """
    if output_attentions and attn_impl != "xla":
        raise ValueError("output_attentions requires attn_impl='xla'")
    if attn_impl in ("flash", "ring"):
        if attn_mask is not None or pad_offsets is not None:
            # these kernels build their causal mask from slot index alone —
            # they cannot see per-row validity/position shifts, so ragged
            # inputs would silently attend pad slots
            raise ValueError(
                f"attn_impl={attn_impl!r} does not support attn_mask/"
                "pad_offsets (ragged batches); use attn_impl='xla'"
            )
        # Fresh-cache-only contract: attention reads the freshly projected
        # K/V, so cached history would be silently dropped.  length is
        # traced under jit (the prefill fns pass a fresh cache by
        # construction); enforce host-side whenever it is concrete.
        if cache is not None and not isinstance(cache.length, jax.core.Tracer):
            if int(cache.length) != 0:
                raise ValueError(
                    f"attn_impl={attn_impl!r} requires a fresh cache "
                    f"(length 0, got {int(cache.length)}): cached history "
                    "is not visible to these kernels"
                )
    b, s = input_ids.shape
    act_dtype = compute_dtype(params)

    # offset: scalar, or [B] per-row lengths (batched speculative decoding)
    offset = cache.length if cache is not None else jnp.zeros((), jnp.int32)
    if positions is None:
        off_rows = offset[:, None] if offset.ndim == 1 else offset
        positions = off_rows + jnp.arange(s, dtype=jnp.int32)[None, :]
        positions = jnp.broadcast_to(positions, (b, s))
        if pad_offsets is not None:
            # left-padded ragged rows: clamp so pad slots get position 0
            # (they are masked out of attention; RoPE just needs validity)
            positions = jnp.maximum(positions - pad_offsets[:, None], 0)

    x = embed_inputs(params, input_ids, config)

    cos, sin = rope_cos_sin(positions, config, dtype=jnp.float32)

    # Masks (shared across layers; sliding-window layers select the local
    # variant inside the scan).
    if cache is not None:
        kv_positions = jnp.arange(cache.max_seq_len, dtype=jnp.int32)
        if pad_offsets is not None:
            kv_positions = kv_positions[None, :] - pad_offsets[:, None]
        # Persist per-slot validity so pad tokens masked out in an earlier
        # chunk stay masked in later calls (the bitmap is the source of
        # truth; slots never written are also False).
        new_tokens_valid = (
            jnp.broadcast_to(attn_mask, (b, s))
            if attn_mask is not None
            else jnp.ones((b, s), dtype=jnp.bool_)
        )
        if offset.ndim == 1:
            cache_valid = jax.vmap(
                lambda row, new, off: lax.dynamic_update_slice(row, new, (off,))
            )(cache.valid, new_tokens_valid, offset)
        else:
            cache_valid = lax.dynamic_update_slice(
                cache.valid, new_tokens_valid, (jnp.zeros((), jnp.int32), offset)
            )
        kv_valid = cache_valid
    else:
        kv_positions = positions
        cache_valid = None
        kv_valid = (
            jnp.broadcast_to(attn_mask, (b, s)) if attn_mask is not None else None
        )
    mask_global = causal_mask(positions, kv_positions, kv_valid=kv_valid)
    if config.sliding_window is not None:
        mask_local = causal_mask(
            positions, kv_positions, window=config.sliding_window, kv_valid=kv_valid
        )
    else:
        mask_local = mask_global

    lp = params["layers"]
    num_layers = config.num_hidden_layers
    is_sliding = jnp.array(
        [config.layer_is_sliding(i) for i in range(num_layers)], dtype=jnp.bool_
    )
    act = ACT2FN[config.hidden_act]

    quantized = cache is not None and cache.quantized
    if cache is not None:
        k_cache, v_cache = cache.k, cache.v
        ks_cache = cache.k_scale if quantized else jnp.zeros((num_layers, 0))
        vs_cache = cache.v_scale if quantized else jnp.zeros((num_layers, 0))
    else:
        # Scan still needs per-layer xs of uniform shape; use zero-size dummies.
        k_cache = jnp.zeros((num_layers, 0), dtype=act_dtype)
        v_cache = jnp.zeros((num_layers, 0), dtype=act_dtype)
        ks_cache = jnp.zeros((num_layers, 0))
        vs_cache = jnp.zeros((num_layers, 0))

    def layer_step(x: jnp.ndarray, xs: tuple) -> tuple[jnp.ndarray, tuple]:
        w, k_l, v_l, ks_l, vs_l, sliding = xs
        x_in = x  # layer input (collected when output_hidden_states)
        written = {}  # int8 mode: slabs+scales stashed by the write hook
        if quantized:

            def kv_update(k, v):
                slabs = update_layer_quantized(
                    k_l, v_l, ks_l, vs_l, k, v, offset
                )
                written["slabs"] = slabs
                if attn_impl == "flash_decode" and k.shape[1] == 1:
                    # the decode kernel reads int8 + scales natively —
                    # hand it the raw slabs as (values, scales) pairs
                    return (slabs[0], slabs[2]), (slabs[1], slabs[3])
                # XLA attention reads the dequantized view; XLA fuses the
                # convert+scale into the einsum operand, so the HBM read
                # of the slab stays int8
                return (
                    dequantize_kv(slabs[0], slabs[2], k.dtype),
                    dequantize_kv(slabs[1], slabs[3], v.dtype),
                )

        elif cache is not None:
            kv_update = lambda k, v: update_layer(k_l, v_l, k, v, offset)
        else:
            kv_update = None
        x, kv_att, attn_weights, moe_aux = run_decoder_layer(
            w, x, config=config, act=act, cos=cos, sin=sin,
            mask_global=mask_global, mask_local=mask_local,
            sliding=sliding, attn_impl=attn_impl, kv_update=kv_update,
            output_attentions=output_attentions,
        )
        if quantized:
            k_l, v_l, ks_l, vs_l = written["slabs"]
        elif cache is not None:
            k_l, v_l = kv_att  # updated cache slabs (flash also writes them)

        ys: tuple = (k_l, v_l, ks_l, vs_l, moe_aux)
        if output_hidden_states:
            ys += (x_in,)
        if output_attentions:
            ys += (attn_weights,)
        return x, ys

    x, scan_out = lax.scan(
        layer_step, x, (lp, k_cache, v_cache, ks_cache, vs_cache, is_sliding),
        unroll=scan_unroll(config),
    )
    new_k, new_v = scan_out[0], scan_out[1]
    new_ks, new_vs = scan_out[2], scan_out[3]
    aux: dict[str, jnp.ndarray] = {}
    if config.is_moe and output_router_losses:
        aux["moe_aux_loss"] = jnp.mean(scan_out[4])  # mean over layers
    pos_idx = 5
    if output_hidden_states:
        aux["hidden_states"] = scan_out[pos_idx]  # [L, B, S, H] layer inputs
        pos_idx += 1
    if output_attentions:
        aux["attentions"] = scan_out[pos_idx]  # [L, B, H, Sq, Skv]

    if skip_logits:
        logits = x[:, -1:, :] if logits_last_only else x
    else:
        logits = final_logits(params, x, config, last_only=logits_last_only)

    new_cache = None
    if cache is not None:
        new_cache = KVCache(
            k=new_k, v=new_v, valid=cache_valid, length=offset + s,
            k_scale=new_ks if quantized else None,
            v_scale=new_vs if quantized else None,
        )

    if output_hidden_states:
        # final normed output appended (reference collects it after the
        # final norm too, llama3.2_model.py:708-713); the same rms_norm is
        # traced inside final_logits — XLA CSEs the duplicate
        aux["final_hidden_state"] = rms_norm(
            x, params["final_norm"], eps=config.rms_norm_eps,
            unit_offset=config.rms_norm_unit_offset,
        )
    if aux:
        return logits, new_cache, aux
    return logits, new_cache
