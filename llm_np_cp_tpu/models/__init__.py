"""Model family assembly (the role of SURVEY §2.6/§2.7's L3 layer).

One generic decoder (`transformer.py`) covers every family; `llama.py`,
`gemma2.py`, and `qwen2.py` bind family-specific config/param naming.  Params are a plain
dict pytree with layer weights stacked on a leading axis for
``lax.scan`` — no weight-owning classes, no global ``weights`` dict
(the reference loads weights inside every constructor,
llama3.2_model.py:369-377; here construction and weights are separate pure
data).
"""

from llm_np_cp_tpu.models.transformer import forward, init_params

__all__ = ["forward", "init_params"]
