"""Llama-3.x family binding.

The reference implements this family twice (CuPy: llama3.2_model.py, NumPy:
llama3.2_model_numpy.py, line-for-line twins — SURVEY §1).  Here the family
is a config preset plus HF checkpoint-name mapping; all math lives in
``models/transformer.py``.
"""

from __future__ import annotations

from llm_np_cp_tpu.config import (
    LLAMA_3_1_8B,
    LLAMA_3_2_1B,
    LLAMA_3_2_3B,
    ModelConfig,
)

# HF checkpoint key → (param pytree path, transpose?) for one decoder layer.
# HF Linear weights are [out_features, in_features]
# (y = x @ W.T — llama3.2_model.py:116-136); we store (in, out), hence the
# transpose at load time.
LAYER_KEY_MAP: dict[str, tuple[str, bool]] = {
    "input_layernorm.weight": ("ln_attn_in", False),
    "self_attn.q_proj.weight": ("q_proj", True),
    "self_attn.k_proj.weight": ("k_proj", True),
    "self_attn.v_proj.weight": ("v_proj", True),
    "self_attn.o_proj.weight": ("o_proj", True),
    "post_attention_layernorm.weight": ("ln_mlp_in", False),
    "mlp.gate_proj.weight": ("gate_proj", True),
    "mlp.up_proj.weight": ("up_proj", True),
    "mlp.down_proj.weight": ("down_proj", True),
    # bias entries are consulted only when the config declares
    # attention_bias / mlp_bias (the loader's host buffers come from
    # param_shapes, which gates on those flags); 1-D, never transposed
    "self_attn.q_proj.bias": ("q_bias", False),
    "self_attn.k_proj.bias": ("k_bias", False),
    "self_attn.v_proj.bias": ("v_bias", False),
    "self_attn.o_proj.bias": ("o_bias", False),
    "mlp.gate_proj.bias": ("gate_bias", False),
    "mlp.up_proj.bias": ("up_bias", False),
    "mlp.down_proj.bias": ("down_bias", False),
}

TOP_KEY_MAP: dict[str, tuple[str, bool]] = {
    "model.embed_tokens.weight": ("embed_tokens", False),
    "model.norm.weight": ("final_norm", False),
    "lm_head.weight": ("lm_head", True),
}

CONFIGS: dict[str, ModelConfig] = {
    "meta-llama/Llama-3.2-1B": LLAMA_3_2_1B,
    "meta-llama/Llama-3.2-3B": LLAMA_3_2_3B,
    "meta-llama/Llama-3.1-8B": LLAMA_3_1_8B,
}
