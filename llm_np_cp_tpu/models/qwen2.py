"""Qwen-2 / Qwen-2.5 family binding (framework extension).

Not in the reference's scope (it implements Llama-3.2 and Gemma-2,
SURVEY §0); included because the architecture is exactly the llama
decoder with Q/K/V projection biases and an UNBIASED o_proj (HF
``Qwen2Attention``) — the bias pattern round 1 flagged as the
silent-wrongness class, now a first-class family.  Checkpoint keys match
the llama layout (``model.layers.N.self_attn.q_proj`` …), so the loader
reuses ``models.llama``'s key maps; the bias leaves are gated by
``ModelConfig.attention_bias`` / ``attention_out_bias`` via
``param_shapes``.  All math lives in ``models/transformer.py``.
"""

from __future__ import annotations

from llm_np_cp_tpu.config import QWEN_2_5_0_5B, QWEN_2_5_1_5B, ModelConfig
from llm_np_cp_tpu.models.llama import LAYER_KEY_MAP, TOP_KEY_MAP  # noqa: F401

CONFIGS: dict[str, ModelConfig] = {
    "Qwen/Qwen2.5-0.5B": QWEN_2_5_0_5B,
    "Qwen/Qwen2.5-1.5B": QWEN_2_5_1_5B,
}
