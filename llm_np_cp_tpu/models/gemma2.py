"""Gemma-2 family binding.

Deltas vs Llama live in config flags consumed by ``models/transformer.py``
(SURVEY §2.7): unit-offset RMSNorm, 4-norm sandwich residual, embedding
scaling, GeGLU, final-logit + attention-logit softcapping, alternating
sliding/global attention.  The last two are implemented here even though the
reference drops them (gemma2_model.py applies neither — every layer is
global and scores are uncapped); ``ModelConfig.reference_parity()`` restores
the reference's simplified behavior for oracle comparisons.
"""

from __future__ import annotations

from llm_np_cp_tpu.config import GEMMA_2_2B, GEMMA_2_9B, ModelConfig
from llm_np_cp_tpu.models.llama import LAYER_KEY_MAP as _LLAMA_LAYER_KEY_MAP

# Gemma-2 checkpoints use llama-style keys plus the two extra per-layer
# norms; post_attention_layernorm moves to the attention-output slot
# (sandwich residual, gemma2_model.py:588-591).
LAYER_KEY_MAP: dict[str, tuple[str, bool]] = {
    **_LLAMA_LAYER_KEY_MAP,
    "post_attention_layernorm.weight": ("ln_attn_out", False),
    "pre_feedforward_layernorm.weight": ("ln_mlp_in", False),
    "post_feedforward_layernorm.weight": ("ln_mlp_out", False),
}

TOP_KEY_MAP: dict[str, tuple[str, bool]] = {
    "model.embed_tokens.weight": ("embed_tokens", False),
    "model.norm.weight": ("final_norm", False),
    "lm_head.weight": ("lm_head", True),
}

CONFIGS: dict[str, ModelConfig] = {
    "google/gemma-2-2b": GEMMA_2_2B,
    "google/gemma-2-9b": GEMMA_2_9B,
}
