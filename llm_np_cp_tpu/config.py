"""Model configuration.

The reference consumes a raw HF ``config.json`` through an ``AttributeDict``
with no validation or defaults (llama3.2_model.py:204-207, 1068-1073).  Here
the consumed key set (SURVEY §2.1) becomes an explicit frozen dataclass so a
config is a static, hashable object that can close over a jitted step.

One dataclass covers both model families; the Gemma-2 deltas
(gemma2_model.py per SURVEY §2.7) are expressed as explicit fields rather
than a parallel class hierarchy:

- ``rms_norm_unit_offset``    — Gemma's (1 + w) RMSNorm parameterization
  (gemma2_model.py:334)
- ``sandwich_norms``          — 4 norms/layer with post-norms inside the
  residual (gemma2_model.py:588-591, 621-643)
- ``scale_embeddings``        — hidden *= sqrt(hidden_size) after lookup
  (gemma2_model.py:738-739)
- ``final_logit_softcapping`` — tanh soft cap on logits (gemma2_model.py:867-870)
- ``attn_logit_softcapping``  — soft cap on attention scores.  Present in the
  Gemma-2 config (gemma2_model.py:48) but NOT applied by the reference; we
  implement it correctly and expose ``reference_parity()`` to reproduce the
  reference's simplified behavior.
- ``sliding_window``          — local attention window, alternating
  local/global layers.  Also dropped by the reference (SURVEY §2.7).
- ``query_pre_attn_scalar``   — Gemma attention scale.  The reference
  computes it and then ignores it (gemma2_model.py:434 vs :541-543); we use
  it (identical for 2B/9B where it equals head_dim, correct for 27B).
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path
from typing import Any, Mapping


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Static architecture description for a decoder-only transformer."""

    model_type: str = "llama"
    vocab_size: int = 128256
    hidden_size: int = 2048
    intermediate_size: int = 8192
    num_hidden_layers: int = 16
    num_attention_heads: int = 32
    num_key_value_heads: int = 8
    head_dim: int = 64
    max_position_embeddings: int = 131072
    rope_theta: float = 500000.0
    rms_norm_eps: float = 1e-5
    hidden_act: str = "silu"
    tie_word_embeddings: bool = True
    attention_bias: bool = False
    # o_proj bias: None = follow attention_bias (HF Llama puts a bias on
    # all four attention projections); False = Qwen-2's pattern (Q/K/V
    # biased, o_proj not)
    attention_out_bias: bool | None = None
    mlp_bias: bool = False

    # --- RoPE scaling (llama-3 style). The reference ignores `rope_scaling`
    # entirely (SURVEY §2.2: "no llama-3 rope scaling"); we support it so
    # Llama-3.1/3.2 long-context positions are correct, and disable it in
    # reference-parity mode.
    rope_scaling_type: str | None = None  # None | "llama3"
    rope_scaling_factor: float = 8.0
    rope_scaling_low_freq_factor: float = 1.0
    rope_scaling_high_freq_factor: float = 4.0
    rope_scaling_original_max_position: int = 8192

    # --- Gemma-2 deltas (SURVEY §2.7) ---
    rms_norm_unit_offset: bool = False
    sandwich_norms: bool = False
    scale_embeddings: bool = False
    final_logit_softcapping: float | None = None
    attn_logit_softcapping: float | None = None
    sliding_window: int | None = None
    # Layers with (layer_idx % 2 == 0) use the sliding window when
    # `sliding_window` is set; odd layers stay global (Gemma-2's hybrid
    # schedule, config key `cache_implementation: hybrid`, gemma2_model.py:104).
    query_pre_attn_scalar: float | None = None

    # --- Layer-scan unroll (performance knob, no numeric effect): unroll
    # the lax.scan over layers so XLA can software-pipeline the per-layer
    # weight stream across layer boundaries.  Part of the config — and so
    # of every jit cache key a config closes over — because an env-var
    # read at trace time silently pins the first-seen value (ADVICE r4).
    # The LLMTPU_SCAN_UNROLL env var still overrides it at TRACE time for
    # bench A/Bs; library users should set this field instead.  Values
    # that don't divide num_hidden_layers degrade to 1.
    scan_unroll: int = 1

    # --- Mixture-of-Experts (framework extension; neither reference family
    # is MoE — SURVEY §2.9 lists EP as N/A — but the framework supports
    # Mixtral-style sparse MLPs so expert parallelism has a real workload).
    num_local_experts: int | None = None
    num_experts_per_tok: int = 2
    moe_capacity_factor: float = 2.0  # per-expert buffer = gs*k/E * this
    moe_group_size: int = 1024  # GShard token-group length (keeps dispatch linear in T)
    router_aux_loss_coef: float = 0.02

    def __post_init__(self) -> None:
        # Note: hidden_size need not equal heads*head_dim (Gemma-2-2B:
        # 2304 hidden, 8 heads of 256), so no divisibility constraint there.
        if self.num_attention_heads % self.num_key_value_heads != 0:
            raise ValueError(
                f"num_attention_heads {self.num_attention_heads} not divisible "
                f"by num_key_value_heads {self.num_key_value_heads}"
            )

    # ------------------------------------------------------------------
    @property
    def is_moe(self) -> bool:
        return self.num_local_experts is not None

    @property
    def o_proj_bias(self) -> bool:
        if self.attention_out_bias is not None:
            return self.attention_out_bias
        return self.attention_bias

    @property
    def num_query_groups(self) -> int:
        return self.num_attention_heads // self.num_key_value_heads

    @property
    def attn_scale(self) -> float:
        """Scale applied to q·k scores.

        Llama: 1/sqrt(head_dim) (llama3.2_model.py:467-469).  Gemma-2:
        query_pre_attn_scalar**-0.5 — the reference assigns this then ignores
        it (gemma2_model.py:434); we apply it.
        """
        if self.query_pre_attn_scalar is not None:
            return float(self.query_pre_attn_scalar) ** -0.5
        return float(self.head_dim) ** -0.5

    def layer_is_sliding(self, layer_idx: int) -> bool:
        return self.sliding_window is not None and layer_idx % 2 == 0

    # ------------------------------------------------------------------
    @classmethod
    def from_hf_dict(cls, d: Mapping[str, Any]) -> "ModelConfig":
        """Build from a raw HF ``config.json`` mapping.

        Mirrors the key set the reference actually reads (SURVEY §2.1) plus
        the Gemma-2 keys it reads-but-drops (sliding_window,
        attn_logit_softcapping).
        """
        model_type = d.get("model_type", "llama")
        num_heads = d["num_attention_heads"]
        head_dim = d.get("head_dim") or d["hidden_size"] // num_heads
        kwargs: dict[str, Any] = dict(
            model_type=model_type,
            vocab_size=d["vocab_size"],
            hidden_size=d["hidden_size"],
            intermediate_size=d["intermediate_size"],
            num_hidden_layers=d["num_hidden_layers"],
            num_attention_heads=num_heads,
            num_key_value_heads=d.get("num_key_value_heads", num_heads),
            head_dim=head_dim,
            max_position_embeddings=d.get("max_position_embeddings", 8192),
            rope_theta=d.get("rope_theta", 10000.0),
            rms_norm_eps=d.get("rms_norm_eps", 1e-6),
            hidden_act=d.get("hidden_act", d.get("hidden_activation", "silu")),
            tie_word_embeddings=d.get("tie_word_embeddings", True),
            attention_bias=d.get("attention_bias", False),
            mlp_bias=d.get("mlp_bias", False),
        )
        if d.get("num_local_experts"):
            kwargs.update(
                num_local_experts=d["num_local_experts"],
                num_experts_per_tok=d.get("num_experts_per_tok", 2),
                router_aux_loss_coef=d.get("router_aux_loss_coef", 0.02),
            )
        rope_scaling = d.get("rope_scaling") or None
        if rope_scaling and rope_scaling.get("rope_type", rope_scaling.get("type")) == "llama3":
            kwargs.update(
                rope_scaling_type="llama3",
                rope_scaling_factor=rope_scaling.get("factor", 8.0),
                rope_scaling_low_freq_factor=rope_scaling.get("low_freq_factor", 1.0),
                rope_scaling_high_freq_factor=rope_scaling.get("high_freq_factor", 4.0),
                rope_scaling_original_max_position=rope_scaling.get(
                    "original_max_position_embeddings", 8192
                ),
            )
        if model_type == "gemma2":
            kwargs.update(
                rms_norm_unit_offset=True,
                sandwich_norms=True,
                scale_embeddings=True,
                final_logit_softcapping=d.get("final_logit_softcapping"),
                attn_logit_softcapping=d.get("attn_logit_softcapping"),
                sliding_window=d.get("sliding_window"),
                query_pre_attn_scalar=d.get("query_pre_attn_scalar"),
                hidden_act=d.get("hidden_activation", d.get("hidden_act", "gelu_pytorch_tanh")),
            )
        if model_type == "qwen2":
            # Qwen-2/2.5: llama architecture with Q/K/V projection biases
            # and an unbiased o_proj (HF Qwen2Attention), untied head on
            # the larger sizes
            kwargs.update(
                attention_bias=True,
                attention_out_bias=False,
                tie_word_embeddings=d.get("tie_word_embeddings", False),
            )
        return cls(**kwargs)

    @classmethod
    def from_json(cls, path: str | Path) -> "ModelConfig":
        with open(path) as f:
            return cls.from_hf_dict(json.load(f))

    def reference_parity(self) -> "ModelConfig":
        """Variant reproducing the reference's *simplified* semantics.

        The reference drops attention-logit softcapping and sliding-window
        attention for Gemma-2 (SURVEY §2.7), divides scores by sqrt(head_dim)
        even when query_pre_attn_scalar differs, and ignores rope_scaling.
        Used for parity testing against the NumPy oracle in reference mode.
        """
        return dataclasses.replace(
            self,
            attn_logit_softcapping=None,
            sliding_window=None,
            query_pre_attn_scalar=None,
            rope_scaling_type=None,
        )


# ----------------------------------------------------------------------
# Presets: the model families the reference targets (SURVEY §0 table) plus
# the BASELINE.md configs 4-5 families.  Values match the published HF
# config.json for each model.
# ----------------------------------------------------------------------

LLAMA_3_2_1B = ModelConfig(
    model_type="llama",
    vocab_size=128256,
    hidden_size=2048,
    intermediate_size=8192,
    num_hidden_layers=16,
    num_attention_heads=32,
    num_key_value_heads=8,
    head_dim=64,
    max_position_embeddings=131072,
    rope_theta=500000.0,
    rms_norm_eps=1e-5,
    tie_word_embeddings=True,
    rope_scaling_type="llama3",
    rope_scaling_factor=32.0,
)

LLAMA_3_2_3B = dataclasses.replace(
    LLAMA_3_2_1B,
    hidden_size=3072,
    intermediate_size=8192,
    num_hidden_layers=28,
    num_attention_heads=24,
    num_key_value_heads=8,
    head_dim=128,
)

LLAMA_3_1_8B = dataclasses.replace(
    LLAMA_3_2_1B,
    hidden_size=4096,
    intermediate_size=14336,
    num_hidden_layers=32,
    num_attention_heads=32,
    num_key_value_heads=8,
    head_dim=128,
    rope_scaling_factor=8.0,
    tie_word_embeddings=False,
)

GEMMA_2_2B = ModelConfig(
    model_type="gemma2",
    vocab_size=256000,
    hidden_size=2304,
    intermediate_size=9216,
    num_hidden_layers=26,
    num_attention_heads=8,
    num_key_value_heads=4,
    head_dim=256,
    max_position_embeddings=8192,
    rope_theta=10000.0,
    rms_norm_eps=1e-6,
    hidden_act="gelu_pytorch_tanh",
    tie_word_embeddings=True,
    rms_norm_unit_offset=True,
    sandwich_norms=True,
    scale_embeddings=True,
    final_logit_softcapping=30.0,
    attn_logit_softcapping=50.0,
    sliding_window=4096,
    query_pre_attn_scalar=256.0,
)

GEMMA_2_9B = dataclasses.replace(
    GEMMA_2_2B,
    hidden_size=3584,
    intermediate_size=14336,
    num_hidden_layers=42,
    num_attention_heads=16,
    num_key_value_heads=8,
    head_dim=256,
)

# The one published Gemma-2 size where query_pre_attn_scalar (hidden /
# num_heads = 4608/32 = 144) differs from head_dim (128) — the scaling
# delta the reference computes and then ignores (gemma2_model.py:434 vs
# :541-543); we apply it, so this preset exercises the correct path.
GEMMA_2_27B = dataclasses.replace(
    GEMMA_2_2B,
    hidden_size=4608,
    intermediate_size=36864,
    num_hidden_layers=46,
    num_attention_heads=32,
    num_key_value_heads=16,
    head_dim=128,
    query_pre_attn_scalar=144.0,
)

QWEN_2_5_0_5B = ModelConfig(
    model_type="qwen2",
    vocab_size=151936,
    hidden_size=896,
    intermediate_size=4864,
    num_hidden_layers=24,
    num_attention_heads=14,
    num_key_value_heads=2,
    head_dim=64,
    max_position_embeddings=32768,
    rope_theta=1000000.0,
    rms_norm_eps=1e-6,
    tie_word_embeddings=True,
    attention_bias=True,
    attention_out_bias=False,
)

QWEN_2_5_1_5B = dataclasses.replace(
    QWEN_2_5_0_5B,
    hidden_size=1536,
    intermediate_size=8960,
    num_hidden_layers=28,
    num_attention_heads=12,
    num_key_value_heads=2,
    head_dim=128,
)

PRESETS: dict[str, ModelConfig] = {
    "meta-llama/Llama-3.2-1B": LLAMA_3_2_1B,
    "meta-llama/Llama-3.2-3B": LLAMA_3_2_3B,
    "meta-llama/Llama-3.1-8B": LLAMA_3_1_8B,
    "google/gemma-2-2b": GEMMA_2_2B,
    "google/gemma-2-9b": GEMMA_2_9B,
    "google/gemma-2-27b": GEMMA_2_27B,
    "Qwen/Qwen2.5-0.5B": QWEN_2_5_0_5B,
    "Qwen/Qwen2.5-1.5B": QWEN_2_5_1_5B,
}


def tiny_config(model_type: str = "llama", **overrides: Any) -> ModelConfig:
    """Small config for tests: real structure, toy sizes."""
    base: dict[str, Any] = dict(
        model_type=model_type,
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=3,
        num_attention_heads=4,
        num_key_value_heads=2,
        head_dim=16,
        max_position_embeddings=512,
        rope_theta=10000.0,
        rms_norm_eps=1e-6,
    )
    if model_type == "gemma2":
        base.update(
            hidden_act="gelu_pytorch_tanh",
            rms_norm_unit_offset=True,
            sandwich_norms=True,
            scale_embeddings=True,
            final_logit_softcapping=30.0,
            attn_logit_softcapping=50.0,
            sliding_window=16,
            query_pre_attn_scalar=16.0,
        )
    if model_type == "qwen2":
        base.update(
            attention_bias=True,
            attention_out_bias=False,
            tie_word_embeddings=True,
        )
    base.update(overrides)
    return ModelConfig(**base)
