"""Pure-function op library (the role of SURVEY §2.2's L1 layer).

Every op is a stateless function over arrays — no weight-owning classes like
the reference's ``Linear_np``/``LlamaRMSNorm_np`` (llama3.2_model.py:116,
237); parameters live in a pytree and are passed in, so the whole model is
one traceable function.
"""

from llm_np_cp_tpu.ops.norms import rms_norm
from llm_np_cp_tpu.ops.rope import rope_cos_sin, apply_rope, rotate_half
from llm_np_cp_tpu.ops.activations import silu, gelu_tanh, ACT2FN, softcap
from llm_np_cp_tpu.ops.attention import gqa_attention, causal_mask

__all__ = [
    "rms_norm",
    "rope_cos_sin",
    "apply_rope",
    "rotate_half",
    "silu",
    "gelu_tanh",
    "softcap",
    "ACT2FN",
    "gqa_attention",
    "causal_mask",
]
