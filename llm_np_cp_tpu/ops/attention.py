"""Grouped-query attention.

Reference behavior (llama3.2_model.py:399-508): project → RoPE → cache →
``repeat_kv_np`` (materializes KV across query groups, :180-196) → full
``q@k.T/sqrt(d)`` score matrix → tril mask (only when q_len>2, :471 — a bug
we do not copy; masks here are computed from positions, never from shape
branches) → softmax (live = custom CUDA kernel, stable) → ``@v`` → o_proj.

TPU-first differences:
- no KV repetition: q is reshaped to [B, S, K, G, D] and contracted against
  the K kv-heads directly — the Gemma-2 table (4 KV heads × 256 dim) never
  gets duplicated in HBM;
- softmax is computed in float32 with max-subtraction (the reference's live
  kernel is also max-stabilized, SURVEY §2.4);
- masks are additive bias built from *positions*, so the same code path is
  correct for prefill (q_len=S), chunked prefill, and decode (q_len=1), and
  sliding-window layers just tighten the predicate;
- layouts keep head_dim last and sequence second ([B, S, H, D]) so KV-cache
  writes are contiguous dynamic-slice updates.
"""

from __future__ import annotations

import jax.numpy as jnp

from llm_np_cp_tpu.ops.activations import softcap as _softcap

NEG_INF = float(jnp.finfo(jnp.float32).min)


def causal_mask(
    q_positions: jnp.ndarray,
    kv_positions: jnp.ndarray,
    *,
    window: int | None = None,
    kv_valid: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Boolean attention predicate.

    q_positions: [B, Sq] absolute positions of the query tokens.
    kv_positions: [Skv] or [B, Skv] absolute positions of cache slots.
    window: if set, also require ``q_pos - kv_pos < window`` (sliding-window
        local attention — the Gemma-2 feature the reference drops, SURVEY §2.7).
    kv_valid: optional [B, Skv] validity of cache slots (slots beyond the
        written length, or padding).

    Returns bool [B, Sq, Skv]; True = attend.
    """
    if kv_positions.ndim == 1:
        kv_positions = kv_positions[None, :]
    q = q_positions[:, :, None]  # [B, Sq, 1]
    kv = kv_positions[:, None, :]  # [B, 1, Skv]
    mask = kv <= q
    if window is not None:
        mask = mask & (q - kv < window)
    if kv_valid is not None:
        mask = mask & kv_valid[:, None, :]
    return mask


def gqa_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mask: jnp.ndarray,
    *,
    scale: float,
    logit_softcap: float | None = None,
    return_weights: bool = False,
) -> jnp.ndarray | tuple[jnp.ndarray, jnp.ndarray]:
    """Attention over grouped KV heads.

    q: [B, Sq, H, D]  (H = K * G query heads)
    k, v: [B, Skv, K, D]
    mask: bool, broadcastable to [B, Sq, Skv] (True = attend)

    Returns [B, Sq, H, D] in q.dtype (weights additionally if requested —
    the reference's ``output_attentions`` surface, llama3.2_model.py:679-706).
    """
    b, sq, h, d = q.shape
    _, skv, kh, _ = k.shape
    g = h // kh
    qg = q.reshape(b, sq, kh, g, d)

    # scores: contract head_dim; accumulate in f32 on the MXU.
    scores = jnp.einsum(
        "bqkgd,bskd->bkgqs", qg, k, preferred_element_type=jnp.float32
    )
    scores = scores * scale
    if logit_softcap is not None:
        scores = _softcap(scores, logit_softcap)

    bias = jnp.where(mask[:, None, None, :, :], 0.0, NEG_INF).astype(jnp.float32)
    scores = scores + bias

    # Stable softmax in f32 (semantics of the reference's live CUDA kernel,
    # llama3.2_model.py:940-952).
    scores = scores - jnp.max(scores, axis=-1, keepdims=True)
    probs = jnp.exp(scores)
    probs = probs / jnp.sum(probs, axis=-1, keepdims=True)

    out = jnp.einsum(
        "bkgqs,bskd->bqkgd", probs.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    out = out.reshape(b, sq, h, d).astype(q.dtype)
    if return_weights:
        return out, probs.reshape(b, h, sq, skv)
    return out
