"""RMS normalization.

Reference semantics: ``x * rsqrt(mean(x^2, -1) + eps) * weight``
(llama3.2_model.py:237-273) with Gemma's ``(1 + w)`` parameterization
(gemma2_model.py:334 stores ``weight + 1`` at load time; we keep the raw
checkpoint weight and add 1 in the op so params stay checkpoint-faithful).

TPU note: the reduction and rsqrt run in float32 regardless of input dtype —
bf16 mean-of-squares loses enough mantissa to move logits; the cast pair
fuses away in XLA.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def rms_norm(
    x: jnp.ndarray,
    weight: jnp.ndarray,
    *,
    eps: float = 1e-6,
    unit_offset: bool = False,
) -> jnp.ndarray:
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    normed = xf * lax.rsqrt(var + eps)
    w = weight.astype(jnp.float32)
    if unit_offset:
        w = w + 1.0
    return (normed * w).astype(dtype)
