"""Sparse Mixture-of-Experts MLP (Mixtral-style top-k routing).

Framework extension: neither reference family is MoE (SURVEY §2.9 lists
expert parallelism as N/A), but a real EP workload needs a real sparse
layer.  The design is the TPU-native dispatch/combine formulation
(GShard lineage): routing becomes two einsums against a one-hot dispatch
tensor, so the whole layer is static-shaped, differentiable, and GSPMD
shards it by annotating the expert axis — the compiler inserts the
all-to-all-equivalent collectives, no hand-written routing backend.

Tokens are processed in *groups* of ≤ ``group_size`` (the GShard group
dimension): the dispatch tensor is ``[G, gs, E, C]`` with per-group
capacity ``C = ceil(gs · k / E · capacity_factor)``, so its size stays
linear in the token count instead of the quadratic blow-up a single
global dispatch tensor would have.

Capacity semantics: each expert owns ``C`` slots per group.  Tokens that
overflow an expert's buffer are *dropped* for that expert (their combine
weight is zero) and pass through the residual unchanged — standard
GShard/Switch behavior, and the price of static shapes under jit.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

from llm_np_cp_tpu.quant import quant_einsum


def _group_split(t: int, group_size: int) -> int:
    """Largest divisor of t that is ≤ group_size (group length gs; G=t/gs)."""
    gs = min(t, group_size)
    while t % gs:
        gs -= 1
    return gs


def moe_mlp(
    x: jnp.ndarray,
    router_w: jnp.ndarray,
    gate_w: jnp.ndarray,
    up_w: jnp.ndarray,
    down_w: jnp.ndarray,
    *,
    act,
    top_k: int,
    capacity_factor: float = 2.0,
    group_size: int = 1024,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Top-k routed SwiGLU experts.

    x: [B, S, H]; router_w: [H, E]; gate_w/up_w: [E, H, I]; down_w: [E, I, H].

    Returns ``(out [B, S, H], aux_loss scalar)`` where aux_loss is the
    load-balancing loss ``E · Σ_e f_e · P_e`` (f_e = fraction of token
    routes sent to expert e, P_e = mean router probability, both over the
    full token set) — the standard Switch/Mixtral auxiliary, ~1 when
    perfectly balanced.
    """
    b, s, h = x.shape
    e = router_w.shape[-1]
    t = b * s
    xt = x.reshape(t, h)

    # Routing in f32 (tiny GEMM; numerics matter more than speed here).
    router_logits = jnp.einsum(
        "th,he->te", xt.astype(jnp.float32), router_w.astype(jnp.float32)
    )
    probs = jax.nn.softmax(router_logits, axis=-1)  # [T, E]
    top_vals, top_idx = lax.top_k(probs, top_k)  # [T, k]
    top_vals = top_vals / jnp.sum(top_vals, axis=-1, keepdims=True)  # renorm (Mixtral)
    # gates: [T, E] — renormalized prob on chosen experts, 0 elsewhere
    gates = jnp.zeros_like(probs).at[jnp.arange(t)[:, None], top_idx].set(top_vals)
    routed = gates > 0.0

    # Group tokens; static per-expert capacity per group.
    gs = _group_split(t, group_size)
    g = t // gs
    capacity = max(1, math.ceil(gs * top_k / e * capacity_factor))
    routed_g = routed.reshape(g, gs, e)
    position = jnp.cumsum(routed_g.astype(jnp.int32), axis=1) - 1  # [G, gs, E]
    keep = routed_g & (position < capacity)
    # one_hot of -1 is the zero row → dropped tokens vanish from dispatch
    dispatch = jax.nn.one_hot(
        jnp.where(keep, position, -1), capacity, dtype=x.dtype
    )  # [G, gs, E, C]

    xg = xt.reshape(g, gs, h)
    expert_in = jnp.einsum(
        "gtec,gth->gech", dispatch, xg, preferred_element_type=jnp.float32
    ).astype(x.dtype)
    gate_h = act(quant_einsum("gech,ehi->geci", expert_in, gate_w)).astype(x.dtype)
    up_h = quant_einsum("gech,ehi->geci", expert_in, up_w).astype(x.dtype)
    expert_out = quant_einsum("geci,eih->gech", gate_h * up_h, down_w).astype(x.dtype)

    combine = dispatch * gates.reshape(g, gs, e).astype(x.dtype)[..., None]
    out = jnp.einsum(
        "gtec,gech->gth", combine, expert_out, preferred_element_type=jnp.float32
    ).astype(x.dtype)

    # Load-balancing auxiliary (f32): fraction of routes per expert × mean prob.
    route_frac = jnp.mean(routed.astype(jnp.float32), axis=0) / top_k  # [E]
    prob_frac = jnp.mean(probs, axis=0)  # [E]
    aux_loss = e * jnp.sum(route_frac * prob_frac)

    return out.reshape(b, s, h), aux_loss
