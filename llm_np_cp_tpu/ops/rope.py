"""Rotary position embeddings.

Reference semantics (llama3.2_model.py:30-82): ``inv_freq = base^(-2i/d)``,
cos/sin built by duplicating the frequency block along the last axis
(``concat([freqs, freqs])``) and rotation applied with the half-split
``rotate_half`` convention: ``q*cos + rotate_half(q)*sin``.

Beyond the reference: llama-3 rope scaling (smooth low/high frequency
interpolation).  The reference reads ``rope_theta`` but ignores the
``rope_scaling`` config block entirely (SURVEY §2.2), which mis-positions
Llama-3.1/3.2 beyond the original 8k context; we implement it and switch it
off in reference-parity mode.

TPU note: cos/sin are computed once per forward from the position vector —
a [S, D] table, negligible next to the matmuls — so there is no precomputed
max-length table eating HBM, and positions can be traced values (cache
offsets) under jit.
"""

from __future__ import annotations

import math

import jax.numpy as jnp

from llm_np_cp_tpu.config import ModelConfig


def _inv_freq(config: ModelConfig) -> jnp.ndarray:
    dim = config.head_dim
    inv_freq = 1.0 / (
        config.rope_theta ** (jnp.arange(0, dim, 2, dtype=jnp.float32) / dim)
    )
    if config.rope_scaling_type == "llama3":
        # Smoothly interpolate: high-frequency (short wavelength) components
        # unchanged, low-frequency components divided by `factor`, linear
        # ramp between the two corner wavelengths.
        factor = config.rope_scaling_factor
        low = config.rope_scaling_low_freq_factor
        high = config.rope_scaling_high_freq_factor
        orig = config.rope_scaling_original_max_position
        wavelen = 2.0 * math.pi / inv_freq
        low_wavelen = orig / low
        high_wavelen = orig / high
        smooth = (orig / wavelen - low) / (high - low)
        scaled = jnp.where(wavelen > low_wavelen, inv_freq / factor, inv_freq)
        interp = (1.0 - smooth) / factor * inv_freq + smooth * inv_freq
        is_medium = (wavelen <= low_wavelen) & (wavelen >= high_wavelen)
        inv_freq = jnp.where(is_medium, interp, scaled)
    return inv_freq


def rope_cos_sin(
    positions: jnp.ndarray, config: ModelConfig, dtype: jnp.dtype = jnp.float32
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """cos/sin tables for ``positions`` (any leading shape) → [..., head_dim]."""
    inv_freq = _inv_freq(config)
    freqs = positions.astype(jnp.float32)[..., None] * inv_freq  # [..., dim/2]
    emb = jnp.concatenate([freqs, freqs], axis=-1)  # [..., dim]
    return jnp.cos(emb).astype(dtype), jnp.sin(emb).astype(dtype)


def rotate_half(x: jnp.ndarray) -> jnp.ndarray:
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([-x2, x1], axis=-1)


def apply_rope(
    x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray
) -> jnp.ndarray:
    """Rotate ``x``: [..., S, n_heads, head_dim] with cos/sin [..., S, head_dim].

    The head axis sits between the sequence axis and head_dim, so cos/sin
    broadcast with one unsqueeze (the reference's ``unsqueeze_dim=1`` on a
    [b, h, s, d] layout — llama3.2_model.py:77-82; we keep [b, s, h, d]
    because it writes into the KV cache without a transpose).
    """
    cos = cos[..., None, :]
    sin = sin[..., None, :]
    return (x * cos + rotate_half(x) * sin).astype(x.dtype)
