"""Activations and soft-capping.

Reference: tanh-approx GELU (llama3.2_model.py:88-89), SiLU (:93-97), the
``ACT2FN_np`` registry (:103-108), and Gemma's final-logit soft cap
``tanh(x/c)*c`` (gemma2_model.py:867-870).
"""

from __future__ import annotations

import jax.nn
import jax.numpy as jnp


def silu(x: jnp.ndarray) -> jnp.ndarray:
    return x * jax.nn.sigmoid(x)


def gelu_tanh(x: jnp.ndarray) -> jnp.ndarray:
    """``gelu_pytorch_tanh``: 0.5x(1 + tanh(sqrt(2/pi)(x + 0.044715 x^3)))."""
    c = 0.7978845608028654  # sqrt(2/pi)
    return 0.5 * x * (1.0 + jnp.tanh(c * (x + 0.044715 * x * x * x)))


def relu(x: jnp.ndarray) -> jnp.ndarray:
    return jnp.maximum(x, 0)


ACT2FN = {
    "silu": silu,
    "gelu_pytorch_tanh": gelu_tanh,
    "relu": relu,
}


def softcap(x: jnp.ndarray, cap: float) -> jnp.ndarray:
    """``tanh(x / cap) * cap`` — Gemma-2 logit/score capping."""
    return jnp.tanh(x / cap) * cap
