"""Fused decode-step attention over the static KV cache.

The decode step attends ONE query token per sequence against the whole
cache slab ([B, S_max, K, D]).  The XLA path computes scores → softmax →
weighted sum as separate HLOs; this kernel streams each KV block through
VMEM once with online-softmax state, the decode analogue of the prefill
flash kernel (ops/pallas/softmax.py lineage; the reference's custom CUDA
kernel role, SURVEY §2.3).

Design choices vs the prefill kernel:
- mask-driven, not position-driven: the caller passes the SAME [B, S_max]
  boolean mask the XLA path uses (cache validity ∧ causality ∧ sliding
  window ∧ ragged-batch pads), so every decode feature — including
  per-row lengths from batched speculative decoding — works unchanged.
- the grouped query heads for one KV head ride along as a tiny [G, D]
  block; decode is HBM-bound on the K/V stream, so MXU shape efficiency
  is irrelevant — the win, if any, is fusion (no [B,H,S] score
  materialization between HLOs).

Benchmark-gated like every kernel here (SURVEY §7 step 7): wired as
``attn_impl="flash_decode"``, default stays XLA.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float(jnp.finfo(jnp.float32).min)


def _decode_kernel(
    q_ref, k_ref, v_ref, mask_ref, o_ref, m_ref, l_ref, acc_ref,
    *, scale: float, softcap: float | None,
):
    j = pl.program_id(1)  # kv block
    nj = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = q_ref[0].astype(jnp.float32)  # [G, D]
    k = k_ref[0].astype(jnp.float32)  # [block_s, D]
    v = v_ref[0].astype(jnp.float32)

    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale  # [G, block_s]
    if softcap is not None:
        s = jnp.tanh(s / softcap) * softcap
    s = jnp.where(mask_ref[0][None, :], s, NEG_INF)

    m_prev = m_ref[:]  # [G, 1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    # re-zero masked slots: exp(NEG_INF - m) underflows to 0 for any real
    # m, but a FULLY-masked row has m == NEG_INF and would get p == 1
    # everywhere, silently averaging V over garbage slots
    p = jnp.where(mask_ref[0][None, :], p, 0.0)
    alpha = jnp.exp(m_prev - m_new)
    l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    m_ref[:] = m_new

    @pl.when(j == nj - 1)
    def _finalize():
        # A row with nothing visible (can't happen for real rows — the
        # current token is always valid) has l == 0 thanks to the p
        # re-zeroing above; emit zeros instead of dividing by zero.
        l = jnp.where(l_ref[:] == 0.0, 1.0, l_ref[:])
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "logit_softcap", "block_s", "interpret"),
)
def decode_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mask: jnp.ndarray,
    *,
    scale: float,
    logit_softcap: float | None = None,
    block_s: int = 512,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """One-token GQA attention against the cache.

    q [B, 1, H, D], k/v [B, S, K, D], mask [B, S] bool (True = visible)
    → [B, 1, H, D].  Equivalent to ``gqa_attention(q, k, v, mask[:,None,:])``
    — verified against it in tests.

    interpret=None auto-selects: compiled on TPU, interpreter elsewhere.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, one, h, d = q.shape
    assert one == 1, f"decode_attention is q_len=1 only, got {one}"
    _, s, kh, _ = k.shape
    g = h // kh
    out_dtype = q.dtype

    # [B, 1, H, D] → [B*K, G, D]; kv → [B*K, S, D]; mask rides per batch.
    qf = q.reshape(b, kh, g, d).reshape(b * kh, g, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * kh, s, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * kh, s, d)

    block_s = min(block_s, max(s, 1))
    s_pad = (-s) % block_s
    if s_pad:
        kf = jnp.pad(kf, ((0, 0), (0, s_pad), (0, 0)))
        vf = jnp.pad(vf, ((0, 0), (0, s_pad), (0, 0)))
        mask = jnp.pad(mask, ((0, 0), (0, s_pad)))  # pads masked out
    sp = s + s_pad

    grid = (b * kh, sp // block_s)
    out = pl.pallas_call(
        functools.partial(_decode_kernel, scale=scale, softcap=logit_softcap),
        out_shape=jax.ShapeDtypeStruct((b * kh, g, d), out_dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, g, d), lambda bk, j: (bk, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_s, d), lambda bk, j: (bk, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_s, d), lambda bk, j: (bk, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_s), lambda bk, j, _kh=kh: (bk // _kh, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, g, d), lambda bk, j: (bk, 0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((g, 1), jnp.float32),
            pltpu.VMEM((g, 1), jnp.float32),
            pltpu.VMEM((g, d), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf, mask)

    return out.reshape(b, kh, g, d).reshape(b, 1, h, d)
