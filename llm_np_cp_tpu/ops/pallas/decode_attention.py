"""Fused decode-step attention over the static KV cache.

The decode step attends ONE query token per sequence against the whole
cache slab ([B, S_max, K, D]).  The XLA path computes scores → softmax →
weighted sum as separate HLOs; this kernel streams each KV block through
VMEM once with online-softmax state, the decode analogue of the prefill
flash kernel (ops/pallas/softmax.py lineage; the reference's custom CUDA
kernel role, SURVEY §2.3).

Design choices vs the prefill kernel:
- mask-driven, not position-driven: the caller passes the SAME [B, S_max]
  boolean mask the XLA path uses (cache validity ∧ causality ∧ sliding
  window ∧ ragged-batch pads), so every decode feature — including
  per-row lengths from batched speculative decoding — works unchanged.
- the grouped query heads for one KV head ride along as a tiny [G, D]
  block; decode is HBM-bound on the K/V stream, so MXU shape efficiency
  is irrelevant — the win, if any, is fusion (no [B,H,S] score
  materialization between HLOs).

Benchmark-gated like every kernel here (SURVEY §7 step 7): wired as
``attn_impl="flash_decode"``, default stays XLA.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float(jnp.finfo(jnp.float32).min)


def _decode_kernel(
    *refs, scale: float, softcap: float | None, quantized: bool,
):
    if quantized:
        (q_ref, k_ref, v_ref, mask_ref, ks_ref, vs_ref,
         o_ref, m_ref, l_ref, acc_ref) = refs
    else:
        q_ref, k_ref, v_ref, mask_ref, o_ref, m_ref, l_ref, acc_ref = refs
    j = pl.program_id(2)  # kv block (innermost: scratch accumulates per (b,kh))
    nj = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = q_ref[0, 0].astype(jnp.float32)  # [G, D]
    k = k_ref[0, :, 0].astype(jnp.float32)  # [block_s, D]
    v = v_ref[0, :, 0].astype(jnp.float32)
    if quantized:
        # int8 cache: HBM streams 1-byte values; dequant happens here in
        # VMEM (the XLA path fuses the same multiply into its einsum)
        k = k * ks_ref[0, :, 0][:, None]
        v = v * vs_ref[0, :, 0][:, None]

    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale  # [G, block_s]
    if softcap is not None:
        s = jnp.tanh(s / softcap) * softcap
    s = jnp.where(mask_ref[0][None, :], s, NEG_INF)

    m_prev = m_ref[:]  # [G, 1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    # re-zero masked slots: exp(NEG_INF - m) underflows to 0 for any real
    # m, but a FULLY-masked row has m == NEG_INF and would get p == 1
    # everywhere, silently averaging V over garbage slots
    p = jnp.where(mask_ref[0][None, :], p, 0.0)
    alpha = jnp.exp(m_prev - m_new)
    l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    m_ref[:] = m_new

    @pl.when(j == nj - 1)
    def _finalize():
        # A row with nothing visible (can't happen for real rows — the
        # current token is always valid) has l == 0 thanks to the p
        # re-zeroing above; emit zeros instead of dividing by zero.
        l = jnp.where(l_ref[:] == 0.0, 1.0, l_ref[:])
        o_ref[0, 0] = (acc_ref[:] / l).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "logit_softcap", "block_s", "interpret"),
)
def decode_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mask: jnp.ndarray,
    *,
    k_scale: jnp.ndarray | None = None,
    v_scale: jnp.ndarray | None = None,
    scale: float,
    logit_softcap: float | None = None,
    block_s: int = 512,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """One-token GQA attention against the cache.

    q [B, 1, H, D], k/v [B, S, K, D], mask [B, S] bool (True = visible)
    → [B, 1, H, D].  Equivalent to ``gqa_attention(q, k, v, mask[:,None,:])``
    — verified against it in tests.

    int8 cache mode: pass k/v as int8 with ``k_scale``/``v_scale``
    [B, S, K] (cache.quantize_kv layout); the kernel streams 1-byte
    values from HBM and dequantizes in VMEM — the combination that would
    otherwise materialize full dequantized slabs per step.

    interpret=None auto-selects: compiled on TPU, interpreter elsewhere.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    quantized = k_scale is not None
    if (
        quantized != (k.dtype == jnp.int8)
        or quantized != (v.dtype == jnp.int8)
        or quantized != (v_scale is not None)
    ):
        raise ValueError(
            "int8 k AND v require both k_scale and v_scale (and vice "
            f"versa); got k={k.dtype}, v={v.dtype}, "
            f"k_scale={'set' if k_scale is not None else None}, "
            f"v_scale={'set' if v_scale is not None else None}"
        )
    b, one, h, d = q.shape
    assert one == 1, f"decode_attention is q_len=1 only, got {one}"
    _, s, kh, _ = k.shape
    g = h // kh
    out_dtype = q.dtype

    # ZERO-COPY contract: decode is HBM-bound on streaming the cache slab,
    # so the kernel reads K/V in their NATIVE [B, S, K, D] layout via 4-D
    # BlockSpecs — no transpose/pad materialization of the slabs (an early
    # version transposed both, doubling the very traffic the kernel exists
    # to avoid).  q's head split [B,1,H,D]→[B,1,K,G,D] is a free reshape.
    qf = q.reshape(b, kh, g, d)  # [B, K, G, D]

    # block_s must divide s (padding k/v would copy the whole slab; Mosaic
    # edge-padding reads undefined bytes that 0*NaN could leak through).
    # Callers size caches to 8-aligned capacities, so the largest divisor
    # ≤ block_s is near block_s in practice; worst case degrades to more
    # grid steps, never to wrong results.
    block_s = min(block_s, max(s, 1))
    while s % block_s:
        block_s -= 1

    grid = (b, kh, s // block_s)
    in_specs = [
        pl.BlockSpec((1, 1, g, d), lambda bi, ki, j: (bi, ki, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_s, 1, d), lambda bi, ki, j: (bi, j, ki, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_s, 1, d), lambda bi, ki, j: (bi, j, ki, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_s), lambda bi, ki, j: (bi, j),
                     memory_space=pltpu.VMEM),
    ]
    operands = [qf, k, v, mask]
    if quantized:
        scale_spec = pl.BlockSpec(
            (1, block_s, 1), lambda bi, ki, j: (bi, j, ki),
            memory_space=pltpu.VMEM,
        )
        in_specs += [scale_spec, scale_spec]
        operands += [k_scale, v_scale]
    out = pl.pallas_call(
        functools.partial(
            _decode_kernel, scale=scale, softcap=logit_softcap,
            quantized=quantized,
        ),
        out_shape=jax.ShapeDtypeStruct((b, kh, g, d), out_dtype),
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, g, d), lambda bi, ki, j: (bi, ki, 0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((g, 1), jnp.float32),
            pltpu.VMEM((g, 1), jnp.float32),
            pltpu.VMEM((g, d), jnp.float32),
        ],
        interpret=interpret,
    )(*operands)

    return out.reshape(b, 1, h, d)
