"""Fused decode-step attention over the static KV cache.

The decode step attends ONE query token per sequence against the whole
cache slab ([B, S_max, K, D]).  The XLA path computes scores → softmax →
weighted sum as separate HLOs over the FULL slab — static shapes mean it
always streams every slot, valid or not.  This kernel streams each KV
block through VMEM once with online-softmax state (the decode analogue
of the prefill flash kernel; the reference's custom CUDA kernel role,
SURVEY §2.3) and additionally SKIPS blocks outside each row's visible
range — the structural advantage a kernel has over XLA here.

Design (round-5 rewrite; the r4 kernel ran at 58% of the XLA path):
- mask-driven, not position-driven: the caller passes the SAME [B, S_max]
  boolean mask the XLA path uses (cache validity ∧ causality ∧ sliding
  window ∧ ragged-batch pads), so every decode feature — including
  per-row lengths from batched speculative decoding — works unchanged.
- per-row block bounds are DERIVED from the mask with two cheap XLA
  reductions and fed through scalar prefetch: the kv-block index map
  clamps into [start_b, nb_b), so blocks before the sliding window or
  past the row's valid length are never DMA'd (a repeated block index
  skips the fetch) and their grid steps do no compute.  Ragged batches
  stream only what each row can see.
- grid is (batch, kv_blocks) and ALL kv heads are processed per block.
  The r4 kernel ran the online-softmax update once per kv head on
  [G, block_s] tiles — G is 4-8, so every VPU op ran at half sublane
  occupancy and the per-op overhead repeated K times per block, which
  profiling pointed at as the 951-vs-1,629 tok/s gap.  Here the per-head
  MXU dots are concatenated into ONE [H, block_s] score tile and the
  entire mask/softcap/exp/max/rescale pipeline runs once per block at
  full width.
- dots take bf16 operands with f32 accumulation (MXU-native, same
  contract as the XLA path's einsums) instead of pre-casting to f32.
- Mosaic requires the last two block dims to be 8/128-aligned or equal
  to the full array dims; taking the full (K, D) trailing dims of the
  native [B, S, K, D] slab satisfies that with ZERO transposes or copies.
- int8 cache mode dequantizes the whole [block_s, K, D] block in VMEM
  with a single multiply (HBM streams 1-byte values + f32 scales).

Benchmark-gated like every kernel here (SURVEY §7 step 7): wired as
``attn_impl="flash_decode"``, default stays XLA, and Generator probes
Mosaic support once at construction, downgrading to XLA with a warning
instead of dying at first dispatch (ops/pallas/support.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float(jnp.finfo(jnp.float32).min)

# VMEM working-set budget for the double-buffered K/V (+scale) blocks.
# v5e VMEM is ~16 MiB/core; leave generous headroom for q/mask/scratch
# and the compiler's own buffers.
_VMEM_BUDGET_BYTES = 8 * 2**20

# ----------------------------------------------------------------------
# AMLA rescaling (PAPERS.md: "AMLA: MUL by ADD in FlashAttention
# Rescaling").  The classic online-softmax block update rescales the
# accumulator and normalizer with alpha = exp(m_prev - m_new) — two
# full-width VPU multiplies (plus one transcendental) per block.  AMLA's
# observation: if the running max is kept on the ln2 grid, alpha is an
# EXACT power of two, and multiplying a float by 2^k is an integer ADD
# on its exponent field.  The serving kernels below (_paged_kernel /
# _ragged_kernel) use this additive-max formulation; quantizing the max
# UP to the grid keeps every exp argument <= 0, so the only numerical
# change is that p = exp(s - m) sits up to one octave lower — the
# final acc/l ratio is mathematically unchanged (parity-pinned against
# the XLA oracle at fp32/bf16/int8 in tests).  Validating the win on
# real HBM traffic is recorded live-TPU debt (README/ROADMAP).
# ----------------------------------------------------------------------
_LN2 = 0.6931471805599453
_LOG2E = 1.4426950408889634
# exponent-step clamp: anything below this underflows every f32 anyway,
# and the clamp keeps k * 2^23 inside int32 (250 * 2^23 < 2^31)
_AMLA_KMIN = -250.0


def _amla_max(m_prev: jnp.ndarray, s: jnp.ndarray) -> jnp.ndarray:
    """New running max, snapped UP to the ln2 grid.  A fully-masked
    block's tile max is NEG_INF (its grid snap overflows to -inf) and
    the maximum keeps m_prev — the running max never leaves the grid
    (or its NEG_INF init) once a real score has been seen."""
    t = jnp.max(s, axis=-1, keepdims=True)
    return jnp.maximum(m_prev, jnp.ceil(t * _LOG2E) * _LN2)


def _amla_steps(m_prev: jnp.ndarray, m_new: jnp.ndarray) -> jnp.ndarray:
    """Rescale exponent delta k <= 0 with alpha = 2^k: both maxes sit on
    the ln2 grid, so the division is an exact integer.  The init case
    (m_prev = NEG_INF) clips to the underflow floor, where the rescale
    of the still-zero accumulator is a no-op by construction."""
    d = (m_prev - m_new) * _LOG2E
    d = jnp.where(jnp.isnan(d), 0.0, d)  # belt: -inf minus -inf
    return jnp.round(jnp.clip(d, _AMLA_KMIN, 0.0)).astype(jnp.int32)


def _amla_rescale(x: jnp.ndarray, k: jnp.ndarray) -> jnp.ndarray:
    """``x * 2^k`` (k int32 <= 0) as an integer add on the f32 exponent
    field — the MUL-by-ADD at the heart of AMLA.  Exponent underflow
    (including x == 0 and the NEG_INF-init case) flushes to zero, which
    is exactly what the multiplicative form's denormal underflow did."""
    k23 = k * (1 << 23)
    xi = jax.lax.bitcast_convert_type(x, jnp.int32)
    ok = (xi & jnp.int32(0x7F800000)) + k23 > 0
    return jnp.where(
        ok, jax.lax.bitcast_convert_type(xi + k23, jnp.float32), 0.0
    )


def _decode_kernel(
    bounds_ref, *refs, scale: float, softcap: float | None, quantized: bool,
    kv_heads: int, group: int,
):
    if quantized:
        (q_ref, k_ref, v_ref, mask_ref, ks_ref, vs_ref,
         o_ref, m_ref, l_ref, acc_ref) = refs
    else:
        q_ref, k_ref, v_ref, mask_ref, o_ref, m_ref, l_ref, acc_ref = refs
    bi = pl.program_id(0)
    j = pl.program_id(1)  # kv block (innermost: scratch accumulates per b)
    nj = pl.num_programs(1)
    start, nb = bounds_ref[0, bi], bounds_ref[1, bi]

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Blocks outside [start, nb) hold nothing visible for this row: their
    # index map repeats a fetched block (no DMA) and the update is skipped.
    @pl.when(start + j < nb)
    def _update():
        mask = mask_ref[0, :, 0]  # [block_s]
        kb = k_ref[0]  # [block_s, K, D]
        vb = v_ref[0]
        dtype = q_ref.dtype
        if quantized:
            # int8 cache: HBM streams 1-byte values; dequant happens here
            # in VMEM, one multiply for the whole block (the XLA path fuses
            # the same multiply into its einsum operand read)
            kb = kb.astype(dtype) * ks_ref[0][..., None].astype(dtype)
            vb = vb.astype(dtype) * vs_ref[0][..., None].astype(dtype)

        # Per-head MXU dots (bf16 × bf16 → f32), concatenated to ONE
        # full-width score tile so the VPU pipeline below runs once per
        # block at [H, block_s] instead of K times at [G, block_s].
        s = jnp.concatenate(
            [
                jax.lax.dot_general(
                    q_ref[0, ki], kb[:, ki], (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                for ki in range(kv_heads)
            ],
            axis=0,
        ) * scale  # [H, block_s]
        if softcap is not None:
            s = jnp.tanh(s / softcap) * softcap
        s = jnp.where(mask[None, :], s, NEG_INF)

        m_prev = m_ref[:]  # [H, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        # re-zero masked slots: exp(NEG_INF - m) underflows to 0 for any
        # real m, but a FULLY-masked row has m == NEG_INF and would get
        # p == 1 everywhere, silently averaging V over garbage slots
        p = jnp.where(mask[None, :], p, 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pb = p.astype(vb.dtype)  # bf16 PV dots, same as the XLA path
        pv = jnp.concatenate(
            [
                jax.lax.dot_general(
                    pb[ki * group:(ki + 1) * group], vb[:, ki],
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                for ki in range(kv_heads)
            ],
            axis=0,
        )  # [H, D]
        acc_ref[:] = acc_ref[:] * alpha + pv
        m_ref[:] = m_new

    @pl.when(j == nj - 1)
    def _finalize():
        # A row with nothing visible (can't happen for real rows — the
        # current token is always valid) has l == 0 thanks to the p
        # re-zeroing above; emit zeros instead of dividing by zero.
        l = jnp.where(l_ref[:] == 0.0, 1.0, l_ref[:])
        o_ref[0] = (acc_ref[:] / l).reshape(o_ref.shape[1:]).astype(o_ref.dtype)


# Sublane alignment for a block_s that is NOT the full cache length.
# block_s is the SECOND-MINOR dim of the bool mask block (1, block_s, 1)
# — Mosaic's sublane tiling for 1-byte element types is (32, 128), so an
# 8-aligned-but-not-32-aligned partial block compiles for the f32/bf16
# K/V specs and then dies on the mask spec.  That is the BENCH_TPU_LIVE_r4
# "block shape divisibility" warm-log failure class (fdec): interpret
# mode hides it, only a hardware compile rejects it.  32 also covers the
# int8 K/V pages, whose own second-minor is block_s-free (full trailing
# dims) but whose scale pages ride the same block length.
_BLOCK_S_ALIGN = 32


def select_block_s(
    s: int, kv_heads: int, head_dim: int, kv_itemsize: int,
    requested: int, quantized: bool,
) -> int:
    """Largest kv-block length that divides ``s``, is 32-aligned (the
    strictest sublane tile among the streamed operands — see
    ``_BLOCK_S_ALIGN``), and keeps the double-buffered K/V(+scale)
    working set inside the VMEM budget.

    Falls back to a single whole-``s`` block for short caches with no
    aligned divisor (then every block dim equals the full array dim,
    which Mosaic always accepts).  Raises for caches that have no
    aligned divisor and are too large for one VMEM block —
    ``decode_attention`` catches that and PADS the cache instead of
    dying (the r4 fdec debt: validate/pad, never hand Mosaic an
    unaligned partial block).
    """
    a = _BLOCK_S_ALIGN
    # hints below the alignment (8/16/24 were valid pre-32) would make
    # the candidate range empty and mis-raise on perfectly divisible
    # caches; the alignment is the real floor, so clamp up to it
    requested = max(requested, a)
    row_bytes = kv_heads * head_dim * kv_itemsize * 2  # K and V
    if quantized:
        row_bytes += kv_heads * 4 * 2  # f32 k/v scales
    cap = max(a, (_VMEM_BUDGET_BYTES // (2 * row_bytes)) // a * a)
    best = 0
    # start aligned DOWN — an unaligned start would step through
    # exclusively unaligned candidates and miss every valid divisor
    for cand in range(min(requested, cap, s) // a * a, a - 1, -a):
        if s % cand == 0:
            best = cand
            break
    if best:
        return best
    # same double-buffering factor as the cap path above
    if 2 * s * row_bytes <= _VMEM_BUDGET_BYTES:
        return s  # single block; block dim == full dim satisfies Mosaic
    raise ValueError(
        f"decode_attention: cache length {s} has no {a}-aligned divisor "
        f"and is too large for a single VMEM block; pad the cache to a "
        f"multiple of {a} (decode_attention does this automatically)"
    )


def _block_bounds(mask: jnp.ndarray, block_s: int, n_blocks: int) -> jnp.ndarray:
    """Per-row [start_block, n_blocks_visible) from the boolean mask —
    two XLA reductions, traced into the surrounding jit.  Rows see
    nothing outside [first_visible, last_visible], so clamping the kv
    block index into these bounds never changes the result (the in-block
    mask still handles partial blocks)."""
    b, s = mask.shape
    pos = jax.lax.broadcasted_iota(jnp.int32, (b, s), 1)
    last = jnp.max(jnp.where(mask, pos, -1), axis=1)  # [B]
    first = jnp.min(jnp.where(mask, pos, s), axis=1)
    nb = jnp.clip(last // block_s + 1, 1, n_blocks)
    start = jnp.clip(first // block_s, 0, nb - 1)
    return jnp.stack([start, nb]).astype(jnp.int32)  # [2, B]


def _paged_kernel(
    meta_ref, tables_ref, *refs,
    scale: float, softcap: float | None, quantized: bool, kv_heads: int,
    group: int, block_s: int,
):
    """Block-table variant of ``_decode_kernel``: the kv grid step fetches
    the POOL block named by the row's table (scalar-prefetched), so the
    serving engine's gather→contiguous copy never materializes.  The
    visibility mask is derived in-kernel from the row's (pad, length)
    scalars instead of a streamed [B, S] mask operand."""
    if quantized:
        (q_ref, k_ref, v_ref, ks_ref, vs_ref,
         o_ref, m_ref, l_ref, acc_ref) = refs
    else:
        q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref = refs
    bi = pl.program_id(0)
    j = pl.program_id(1)
    nj = pl.num_programs(1)
    start, nb = meta_ref[0, bi], meta_ref[1, bi]
    pad, length = meta_ref[2, bi], meta_ref[3, bi]

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(start + j < nb)
    def _update():
        # rank-2 iota over the minor dim — Mosaic rejects rank-1 iota on
        # TPU (the r3-postmortem failure class; interpret mode hides it)
        pos = (start + j) * block_s + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_s), 1
        )
        mask = (pos >= pad) & (pos < length)  # [1, block_s]
        kb = k_ref[0]  # [block_s, K, D]
        vb = v_ref[0]
        dtype = q_ref.dtype
        if quantized:
            # int8 pool blocks: HBM streams 1-byte values + f32 scale
            # pages; dequant is one VMEM multiply per block (same
            # contract as _decode_kernel's int8 mode)
            kb = kb.astype(dtype) * ks_ref[0][..., None].astype(dtype)
            vb = vb.astype(dtype) * vs_ref[0][..., None].astype(dtype)
        s = jnp.concatenate(
            [
                jax.lax.dot_general(
                    q_ref[0, ki], kb[:, ki], (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                for ki in range(kv_heads)
            ],
            axis=0,
        ) * scale  # [H, block_s]
        if softcap is not None:
            s = jnp.tanh(s / softcap) * softcap
        s = jnp.where(mask, s, NEG_INF)

        # AMLA additive-max update: the running max lives on the ln2
        # grid, so the block rescale is an exponent-field integer add
        # instead of an exp() + two full-width multiplies
        m_prev = m_ref[:]
        m_new = _amla_max(m_prev, s)
        p = jnp.exp(s - m_new)
        p = jnp.where(mask, p, 0.0)
        k_steps = _amla_steps(m_prev, m_new)
        l_ref[:] = (_amla_rescale(l_ref[:], k_steps)
                    + jnp.sum(p, axis=-1, keepdims=True))
        pb = p.astype(vb.dtype)
        pv = jnp.concatenate(
            [
                jax.lax.dot_general(
                    pb[ki * group:(ki + 1) * group], vb[:, ki],
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                for ki in range(kv_heads)
            ],
            axis=0,
        )
        acc_ref[:] = _amla_rescale(acc_ref[:], k_steps) + pv
        m_ref[:] = m_new

    @pl.when(j == nj - 1)
    def _finalize():
        l = jnp.where(l_ref[:] == 0.0, 1.0, l_ref[:])
        o_ref[0] = (acc_ref[:] / l).reshape(o_ref.shape[1:]).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("scale", "logit_softcap", "interpret")
)
def paged_decode_attention(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    tables: jnp.ndarray,
    lengths: jnp.ndarray,
    pads: jnp.ndarray,
    *,
    k_scale: jnp.ndarray | None = None,
    v_scale: jnp.ndarray | None = None,
    scale: float,
    logit_softcap: float | None = None,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """One-token GQA attention straight off a paged KV pool.

    q [B, 1, H, D]; k_pages/v_pages [NB, BS, K, D] (ONE layer's pool
    slab, serve/block_pool.py layout); tables [B, MB] int32 block ids
    (scratch-0 padded past each row's allocation); lengths [B] — visible
    slots per row (the current token's K/V already written at slot
    lengths-1); pads [B] — left-pad slots to skip.  → [B, 1, H, D].

    Row b sees pool slot ``tables[b, pos // BS] * BS + pos % BS`` for
    logical positions ``pads[b] <= pos < lengths[b]`` — equivalent to
    gathering the row's blocks into a contiguous [B, MB*BS, K, D] view
    and running ``decode_attention`` with the matching mask (pinned in
    tests), but the gather never materializes: each grid step DMAs one
    pool block found through the scalar-prefetched table, and blocks
    outside [pads//BS, ceil(lengths/BS)) are skipped entirely.

    int8 pool mode: pass k_pages/v_pages as int8 with ``k_scale``/
    ``v_scale`` [NB, BS, K] f32 scale pages (the block_pool quantized
    layout); the kernel streams 1-byte blocks and dequantizes in VMEM.

    This is the serving-engine decode kernel (``attn_impl="paged"`` in
    ServeEngine, kernel-gated via ops/pallas/support.py).
    interpret=None auto-selects like decode_attention.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    quantized = k_scale is not None
    if (
        quantized != (k_pages.dtype == jnp.int8)
        or quantized != (v_pages.dtype == jnp.int8)
        or quantized != (v_scale is not None)
    ):
        raise ValueError(
            "int8 k_pages AND v_pages require both k_scale and v_scale "
            f"pages (and vice versa); got k={k_pages.dtype}, "
            f"v={v_pages.dtype}, "
            f"k_scale={'set' if k_scale is not None else None}, "
            f"v_scale={'set' if v_scale is not None else None}"
        )
    b, one, h, d = q.shape
    assert one == 1, f"paged_decode_attention is q_len=1 only, got {one}"
    nb_pool, block_s, kh, _ = k_pages.shape
    g = h // kh
    mb = tables.shape[1]

    qf = q.reshape(b, kh, g, d)
    start = jnp.clip(pads // block_s, 0, jnp.maximum(mb - 1, 0))
    nb = jnp.clip(-(-lengths // block_s), 1, mb)
    meta = jnp.stack([start, nb, pads, lengths]).astype(jnp.int32)  # [4, B]

    def _kv_map(bi, j, meta_ref, tables_ref):
        jj = jnp.minimum(meta_ref[0, bi] + j, meta_ref[1, bi] - 1)
        return (tables_ref[bi, jj], 0, 0, 0)

    def _scale_map(bi, j, meta_ref, tables_ref):
        jj = jnp.minimum(meta_ref[0, bi] + j, meta_ref[1, bi] - 1)
        return (tables_ref[bi, jj], 0, 0)

    kv_spec = pl.BlockSpec((1, block_s, kh, d), _kv_map,
                           memory_space=pltpu.VMEM)
    in_specs = [
        pl.BlockSpec(
            (1, kh, g, d),
            lambda bi, j, meta_ref, tables_ref: (bi, 0, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        kv_spec,
        kv_spec,
    ]
    operands = [qf, k_pages, v_pages]
    if quantized:
        scale_spec = pl.BlockSpec((1, block_s, kh), _scale_map,
                                  memory_space=pltpu.VMEM)
        in_specs += [scale_spec, scale_spec]
        operands += [k_scale, v_scale]
    out = pl.pallas_call(
        functools.partial(
            _paged_kernel, scale=scale, softcap=logit_softcap,
            quantized=quantized, kv_heads=kh, group=g, block_s=block_s,
        ),
        out_shape=jax.ShapeDtypeStruct((b, kh, g, d), q.dtype),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, mb),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (1, kh, g, d),
                lambda bi, j, meta_ref, tables_ref: (bi, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            scratch_shapes=[
                pltpu.VMEM((h, 1), jnp.float32),
                pltpu.VMEM((h, 1), jnp.float32),
                pltpu.VMEM((h, d), jnp.float32),
            ],
        ),
        interpret=interpret,
    )(meta, tables, *operands)

    return out.reshape(b, 1, h, d)


# ----------------------------------------------------------------------
# Ragged mixed prefill+decode attention (the unified-tick kernel)
# ----------------------------------------------------------------------

# Query-tile width for the ragged kernel's packed token axis.  Every
# row's token segment is padded up to a multiple of this so each q tile
# belongs to exactly ONE row (the scalar-prefetched tile metadata then
# names that row's block table).  8 = the f32 sublane tile; a decode row
# costs one tile (7 masked query lanes) — acceptable, because the win of
# the unified tick is ONE dispatch streaming the weights once for
# prefill AND decode, not per-lane occupancy.
RAGGED_Q_TILE = 8

# meta rows for _ragged_kernel (computed in-graph per layer — the
# sliding-window bound is a traced per-layer value)
_RM_START, _RM_NB, _RM_PAD, _RM_QPOS0, _RM_QLEN, _RM_ROW, _RM_WIN = range(7)


def _ragged_kernel(
    meta_ref, tables_ref, *refs,
    scale: float, softcap: float | None, quantized: bool, kv_heads: int,
    group: int, block_s: int, q_tile: int, head_dim: int,
):
    """Mixed-batch block-table attention: each q tile holds up to
    ``q_tile`` consecutive tokens of ONE row (a prefill-chunk slice, or a
    decode row's single token with the tail masked), and the kv grid
    step fetches the pool block named by the row's scalar-prefetched
    table — the generalization of ``_paged_kernel`` from one query row
    to a query tile.  Visibility is derived in-kernel from the tile's
    (pad, qpos0, qlen, window) scalars: token i at cache slot
    ``qpos0 + i`` sees kv slots in
    ``[max(pad, slot - win + 1), slot]`` — causal within the tile's own
    freshly-written K/V too, because the caller scatters the whole
    packed batch into the pool before attending (same discipline as the
    paged decode step)."""
    if quantized:
        (q_ref, k_ref, v_ref, ks_ref, vs_ref,
         o_ref, m_ref, l_ref, acc_ref) = refs
    else:
        q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref = refs
    ti = pl.program_id(0)
    j = pl.program_id(1)
    nj = pl.num_programs(1)
    start, nb = meta_ref[_RM_START, ti], meta_ref[_RM_NB, ti]
    pad, qpos0 = meta_ref[_RM_PAD, ti], meta_ref[_RM_QPOS0, ti]
    qlen, win = meta_ref[_RM_QLEN, ti], meta_ref[_RM_WIN, ti]

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(start + j < nb)
    def _update():
        # rank-2 iota (Mosaic rejects rank-1 iota on TPU)
        q_idx = jax.lax.broadcasted_iota(jnp.int32, (q_tile, block_s), 0)
        kv_pos = (start + j) * block_s + jax.lax.broadcasted_iota(
            jnp.int32, (q_tile, block_s), 1
        )
        q_slot = qpos0 + q_idx
        mask = (
            (q_idx < qlen)
            & (kv_pos >= pad)
            & (kv_pos > q_slot - win)  # sliding window (win huge = global)
            & (kv_pos <= q_slot)       # causal
        )  # [q_tile, block_s]
        kb = k_ref[0]  # [block_s, K, D]
        vb = v_ref[0]
        dtype = q_ref.dtype
        if quantized:
            kb = kb.astype(dtype) * ks_ref[0][..., None].astype(dtype)
            vb = vb.astype(dtype) * vs_ref[0][..., None].astype(dtype)
        # per-kv-head MXU dots over the whole tile, concatenated to ONE
        # [K*q_tile*G, block_s] score sheet (rows ordered (ki, qi, gi))
        # so the mask/softcap/exp/rescale VPU pipeline runs once per
        # block at full width — the _decode_kernel r5 lesson applied
        s = jnp.concatenate(
            [
                jax.lax.dot_general(
                    q_ref[:, ki].reshape(q_tile * group, head_dim),
                    kb[:, ki], (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                for ki in range(kv_heads)
            ],
            axis=0,
        ) * scale  # [K*q_tile*G, block_s]
        if softcap is not None:
            s = jnp.tanh(s / softcap) * softcap
        # mask rows order (qi, gi), identical for every kv head
        mask_qg = jnp.broadcast_to(
            mask[:, None, :], (q_tile, group, block_s)
        ).reshape(q_tile * group, block_s)
        mask_full = jnp.concatenate([mask_qg] * kv_heads, axis=0)
        s = jnp.where(mask_full, s, NEG_INF)

        # AMLA additive-max update (see _amla_rescale): ln2-grid max,
        # block rescale = exponent-field integer add, not a multiply
        m_prev = m_ref[:]
        m_new = _amla_max(m_prev, s)
        p = jnp.exp(s - m_new)
        # re-zero masked slots: a FULLY-masked query row (dead packing
        # lane) has m == NEG_INF and would otherwise get p == 1
        p = jnp.where(mask_full, p, 0.0)
        k_steps = _amla_steps(m_prev, m_new)
        l_ref[:] = (_amla_rescale(l_ref[:], k_steps)
                    + jnp.sum(p, axis=-1, keepdims=True))
        pb = p.astype(vb.dtype)
        pv = jnp.concatenate(
            [
                jax.lax.dot_general(
                    pb[ki * q_tile * group:(ki + 1) * q_tile * group],
                    vb[:, ki], (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                for ki in range(kv_heads)
            ],
            axis=0,
        )  # [K*q_tile*G, D]
        acc_ref[:] = _amla_rescale(acc_ref[:], k_steps) + pv
        m_ref[:] = m_new

    @pl.when(j == nj - 1)
    def _finalize():
        l = jnp.where(l_ref[:] == 0.0, 1.0, l_ref[:])
        acc = acc_ref[:] / l
        for ki in range(kv_heads):
            o_ref[:, ki] = (
                acc[ki * q_tile * group:(ki + 1) * q_tile * group]
                .reshape(q_tile, group, head_dim)
                .astype(o_ref.dtype)
            )


@functools.partial(
    jax.jit, static_argnames=("scale", "logit_softcap", "interpret")
)
def ragged_paged_attention(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    tables: jnp.ndarray,
    tile_row: jnp.ndarray,
    tile_qpos0: jnp.ndarray,
    tile_qlen: jnp.ndarray,
    pads: jnp.ndarray,
    window: jnp.ndarray,
    *,
    k_scale: jnp.ndarray | None = None,
    v_scale: jnp.ndarray | None = None,
    scale: float,
    logit_softcap: float | None = None,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Mixed prefill+decode GQA attention straight off a paged KV pool.

    One invocation handles a PACKED batch of rows with heterogeneous
    query lengths — prefill-chunk slices and single-token decode rows —
    against the same pool slabs (Ragged Paged Attention, the
    unified-tick kernel).

    q [T, H, D] — the packed token axis: each row's segment occupies
    consecutive, ``RAGGED_Q_TILE``-aligned positions (the serve engine's
    packer guarantees this; dead lanes between segments are masked via
    ``tile_qlen``).  k_pages/v_pages [NB, BS, K, D] — ONE layer's pool
    slab.  tables [R, MB] int32 block ids per engine row.  Per TILE
    (T / RAGGED_Q_TILE entries): ``tile_row`` — the owning engine row,
    ``tile_qpos0`` — the cache slot of the tile's first token,
    ``tile_qlen`` — live tokens in the tile (0 = dead padding tile).
    pads [R] — left-pad slots per row.  window — traced int32 scalar:
    sliding-window width for this layer (pass a huge value for global
    layers; the per-layer flag stays traced, so one compile serves
    both).  → [T, H, D].

    Token i of a tile sees kv slots ``[max(pad, slot_i - window + 1),
    slot_i]`` where ``slot_i = tile_qpos0 + i`` — exactly the visibility
    the phase-split engine's chunked prefill mask + paged decode step
    encode, so outputs are parity-testable against both.  Blocks outside
    the tile's visible range are never DMA'd (clamped index map, same
    skip as ``paged_decode_attention``).

    int8 pool mode: k_scale/v_scale [NB, BS, K] f32 scale pages ride
    along and the kernel dequantizes per block in VMEM.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    quantized = k_scale is not None
    if (
        quantized != (k_pages.dtype == jnp.int8)
        or quantized != (v_pages.dtype == jnp.int8)
        or quantized != (v_scale is not None)
    ):
        raise ValueError(
            "int8 k_pages AND v_pages require both k_scale and v_scale "
            f"pages (and vice versa); got k={k_pages.dtype}, "
            f"v={v_pages.dtype}, "
            f"k_scale={'set' if k_scale is not None else None}, "
            f"v_scale={'set' if v_scale is not None else None}"
        )
    t, h, d = q.shape
    qt = RAGGED_Q_TILE
    if t % qt:
        raise ValueError(
            f"packed token axis ({t}) must be a multiple of "
            f"RAGGED_Q_TILE ({qt})"
        )
    nt = t // qt
    if tile_row.shape != (nt,):
        raise ValueError(
            f"tile metadata must have T/RAGGED_Q_TILE = {nt} entries, "
            f"got {tile_row.shape}"
        )
    nb_pool, block_s, kh, _ = k_pages.shape
    g = h // kh
    mb = tables.shape[1]

    qf = q.reshape(t, kh, g, d)
    # per-tile kv block bounds: the window lower bound is tightest at the
    # tile's FIRST token; the causal upper bound is set by its LAST live
    # token.  The in-kernel mask handles per-token exactness — these only
    # decide which blocks are streamed at all.
    row_pad = pads[tile_row]
    lo = jnp.maximum(row_pad, tile_qpos0 - window + 1)
    hi = tile_qpos0 + jnp.maximum(tile_qlen, 1) - 1
    start = jnp.clip(lo // block_s, 0, jnp.maximum(mb - 1, 0))
    nb = jnp.clip(hi // block_s + 1, 1, mb)
    meta = jnp.stack([
        start, nb, row_pad, tile_qpos0, tile_qlen, tile_row,
        jnp.broadcast_to(window, tile_row.shape),
    ]).astype(jnp.int32)  # [7, NT]

    def _kv_map(ti, j, meta_ref, tables_ref):
        row = meta_ref[_RM_ROW, ti]
        jj = jnp.minimum(
            meta_ref[_RM_START, ti] + j, meta_ref[_RM_NB, ti] - 1
        )
        return (tables_ref[row, jj], 0, 0, 0)

    def _scale_map(ti, j, meta_ref, tables_ref):
        row = meta_ref[_RM_ROW, ti]
        jj = jnp.minimum(
            meta_ref[_RM_START, ti] + j, meta_ref[_RM_NB, ti] - 1
        )
        return (tables_ref[row, jj], 0, 0)

    kv_spec = pl.BlockSpec((1, block_s, kh, d), _kv_map,
                           memory_space=pltpu.VMEM)
    q_spec = pl.BlockSpec(
        (qt, kh, g, d),
        lambda ti, j, meta_ref, tables_ref: (ti, 0, 0, 0),
        memory_space=pltpu.VMEM,
    )
    in_specs = [q_spec, kv_spec, kv_spec]
    operands = [qf, k_pages, v_pages]
    if quantized:
        scale_spec = pl.BlockSpec((1, block_s, kh), _scale_map,
                                  memory_space=pltpu.VMEM)
        in_specs += [scale_spec, scale_spec]
        operands += [k_scale, v_scale]
    out = pl.pallas_call(
        functools.partial(
            _ragged_kernel, scale=scale, softcap=logit_softcap,
            quantized=quantized, kv_heads=kh, group=g, block_s=block_s,
            q_tile=qt, head_dim=d,
        ),
        out_shape=jax.ShapeDtypeStruct((t, kh, g, d), q.dtype),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(nt, mb),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (qt, kh, g, d),
                lambda ti, j, meta_ref, tables_ref: (ti, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            scratch_shapes=[
                pltpu.VMEM((kh * qt * g, 1), jnp.float32),
                pltpu.VMEM((kh * qt * g, 1), jnp.float32),
                pltpu.VMEM((kh * qt * g, d), jnp.float32),
            ],
        ),
        interpret=interpret,
    )(meta, tables, *operands)

    return out.reshape(t, h, d)


def ragged_paged_attention_xla(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    tables: jnp.ndarray,
    tok_row: jnp.ndarray,
    tok_slot: jnp.ndarray,
    tok_live: jnp.ndarray,
    pads: jnp.ndarray,
    window: jnp.ndarray,
    *,
    k_scale: jnp.ndarray | None = None,
    v_scale: jnp.ndarray | None = None,
    scale: float,
    logit_softcap: float | None = None,
) -> jnp.ndarray:
    """XLA reference/fallback for ``ragged_paged_attention`` (per-TOKEN
    metadata instead of per-tile): gathers each engine row's blocks into
    a contiguous view and runs the standard masked GQA attention with
    every packed token as its own batch row — the mixed-step analogue of
    the engine's gather decode path.  Materializes [T, S_max, K, D], so
    it is the PROBE-FAILURE fallback and the parity oracle, not the fast
    path."""
    t, h, d = q.shape
    _, block_s, kh, _ = k_pages.shape
    mb = tables.shape[1]
    s_max = mb * block_s

    def gathered(pages, scales):
        view = pages[tables].reshape(tables.shape[0], s_max, kh, d)
        if scales is None:
            return view
        sv = scales[tables].reshape(tables.shape[0], s_max, kh)
        from llm_np_cp_tpu.cache import dequantize_kv

        return dequantize_kv(view, sv, q.dtype)

    k_rows = gathered(k_pages, k_scale)
    v_rows = gathered(v_pages, v_scale)
    k_t = k_rows[tok_row]  # [T, S_max, K, D]
    v_t = v_rows[tok_row]
    kv_idx = jnp.arange(s_max, dtype=jnp.int32)[None, :]
    lower = jnp.maximum(pads[tok_row], tok_slot - window + 1)[:, None]
    mask = (
        (kv_idx >= lower) & (kv_idx <= tok_slot[:, None])
        & tok_live[:, None]
    )  # [T, S_max]
    from llm_np_cp_tpu.ops.attention import gqa_attention

    out = gqa_attention(
        q[:, None], k_t, v_t, mask[:, None, :],
        scale=scale, logit_softcap=logit_softcap,
    )
    return out[:, 0]


@functools.partial(
    jax.jit,
    static_argnames=("scale", "logit_softcap", "block_s", "interpret"),
)
def decode_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mask: jnp.ndarray,
    *,
    k_scale: jnp.ndarray | None = None,
    v_scale: jnp.ndarray | None = None,
    scale: float,
    logit_softcap: float | None = None,
    block_s: int = 512,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """One-token GQA attention against the cache.

    q [B, 1, H, D], k/v [B, S, K, D], mask [B, S] bool (True = visible)
    → [B, 1, H, D].  Equivalent to ``gqa_attention(q, k, v, mask[:,None,:])``
    — verified against it in tests.

    int8 cache mode: pass k/v as int8 with ``k_scale``/``v_scale``
    [B, S, K] (cache.quantize_kv layout); the kernel streams 1-byte
    values from HBM and dequantizes in VMEM — the combination that would
    otherwise materialize full dequantized slabs per step.

    interpret=None auto-selects: compiled on TPU, interpreter elsewhere.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    quantized = k_scale is not None
    if (
        quantized != (k.dtype == jnp.int8)
        or quantized != (v.dtype == jnp.int8)
        or quantized != (v_scale is not None)
    ):
        raise ValueError(
            "int8 k AND v require both k_scale and v_scale (and vice "
            f"versa); got k={k.dtype}, v={v.dtype}, "
            f"k_scale={'set' if k_scale is not None else None}, "
            f"v_scale={'set' if v_scale is not None else None}"
        )
    b, one, h, d = q.shape
    assert one == 1, f"decode_attention is q_len=1 only, got {one}"
    _, s, kh, _ = k.shape
    g = h // kh
    out_dtype = q.dtype

    # ZERO-COPY contract: decode is HBM-bound on streaming the cache slab,
    # so the kernel reads K/V in their NATIVE [B, S, K, D] layout via 4-D
    # BlockSpecs whose trailing (K, D) dims are the FULL array dims — no
    # transpose/pad materialization of the slabs, and Mosaic's trailing-
    # dims alignment rule is satisfied for any K/D.  q's head split
    # [B,1,H,D]→[B,K,G,D] is a free reshape.
    qf = q.reshape(b, kh, g, d)  # [B, K, G, D]

    try:
        block_s = select_block_s(
            s, kh, d, jnp.dtype(k.dtype).itemsize, block_s, quantized
        )
    except ValueError:
        # no aligned divisor and too large for one block: PAD the cache
        # axis to the alignment and mask the tail off (the r4 fdec fix —
        # a few dead slots beat a Mosaic rejection at first dispatch)
        s_pad = -(-s // _BLOCK_S_ALIGN) * _BLOCK_S_ALIGN
        pad = [(0, 0), (0, s_pad - s), (0, 0), (0, 0)]
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)
        mask = jnp.pad(mask, [(0, 0), (0, s_pad - s)])  # False = invisible
        if quantized:
            k_scale = jnp.pad(k_scale, [(0, 0), (0, s_pad - s), (0, 0)])
            v_scale = jnp.pad(v_scale, [(0, 0), (0, s_pad - s), (0, 0)])
        s = s_pad
        block_s = select_block_s(
            s, kh, d, jnp.dtype(k.dtype).itemsize, block_s, quantized
        )
    mask3 = mask[:, :, None]  # [B, S, 1]: trailing dims (block_s, 1)
    n_blocks = s // block_s
    bounds = _block_bounds(mask, block_s, n_blocks)

    # kv blocks clamp into the row's visible range: a clamped (repeated)
    # index skips the DMA, so invisible blocks are never streamed
    def _kv_map(bi, j, bounds_ref):
        jj = jnp.minimum(bounds_ref[0, bi] + j, bounds_ref[1, bi] - 1)
        return (bi, jj, 0, 0)

    def _kv3_map(bi, j, bounds_ref):
        jj = jnp.minimum(bounds_ref[0, bi] + j, bounds_ref[1, bi] - 1)
        return (bi, jj, 0)

    in_specs = [
        pl.BlockSpec((1, kh, g, d), lambda bi, j, bounds_ref: (bi, 0, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_s, kh, d), _kv_map, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_s, kh, d), _kv_map, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_s, 1), _kv3_map, memory_space=pltpu.VMEM),
    ]
    operands = [qf, k, v, mask3]
    if quantized:
        scale_spec = pl.BlockSpec(
            (1, block_s, kh), _kv3_map, memory_space=pltpu.VMEM
        )
        in_specs += [scale_spec, scale_spec]
        operands += [k_scale, v_scale]
    out = pl.pallas_call(
        functools.partial(
            _decode_kernel, scale=scale, softcap=logit_softcap,
            quantized=quantized, kv_heads=kh, group=g,
        ),
        out_shape=jax.ShapeDtypeStruct((b, kh, g, d), out_dtype),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, n_blocks),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (1, kh, g, d), lambda bi, j, bounds_ref: (bi, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            scratch_shapes=[
                pltpu.VMEM((h, 1), jnp.float32),
                pltpu.VMEM((h, 1), jnp.float32),
                pltpu.VMEM((h, d), jnp.float32),
            ],
        ),
        interpret=interpret,
    )(bounds, *operands)

    return out.reshape(b, 1, h, d)
