"""Fused decode-step attention over the static KV cache.

The decode step attends ONE query token per sequence against the whole
cache slab ([B, S_max, K, D]).  The XLA path computes scores → softmax →
weighted sum as separate HLOs; this kernel streams each KV block through
VMEM once with online-softmax state, the decode analogue of the prefill
flash kernel (ops/pallas/softmax.py lineage; the reference's custom CUDA
kernel role, SURVEY §2.3).

Design choices vs the prefill kernel:
- mask-driven, not position-driven: the caller passes the SAME [B, S_max]
  boolean mask the XLA path uses (cache validity ∧ causality ∧ sliding
  window ∧ ragged-batch pads), so every decode feature — including
  per-row lengths from batched speculative decoding — works unchanged.
- grid is (batch, kv_blocks) and ALL kv heads are processed inside the
  kernel per block (static unroll over K).  Mosaic requires the last two
  block dims to be 8/128-aligned or equal to the full array dims; taking
  the full (K, D) trailing dims of the native [B, S, K, D] slab satisfies
  that with ZERO transposes or copies, and each cache block is streamed
  through VMEM exactly once per step (the r3 layout with K in the grid
  was rejected by Mosaic on hardware — block (1, block_s, 1, d) has an
  unaligned second-minor dim of 1).
- decode is HBM-bound on the K/V stream, so MXU shape efficiency of the
  tiny [G, D] query blocks is irrelevant — the win is fusion (no
  [B, H, S] score materialization between HLOs).

Benchmark-gated like every kernel here (SURVEY §7 step 7): wired as
``attn_impl="flash_decode"``, default stays XLA, and Generator probes
Mosaic support once at construction, downgrading to XLA with a warning
instead of dying at first dispatch (ops/pallas/support.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float(jnp.finfo(jnp.float32).min)

# VMEM working-set budget for the double-buffered K/V (+scale) blocks.
# v5e VMEM is ~16 MiB/core; leave generous headroom for q/mask/scratch
# and the compiler's own buffers.
_VMEM_BUDGET_BYTES = 8 * 2**20


def _decode_kernel(
    *refs, scale: float, softcap: float | None, quantized: bool,
    kv_heads: int, group: int,
):
    if quantized:
        (q_ref, k_ref, v_ref, mask_ref, ks_ref, vs_ref,
         o_ref, m_ref, l_ref, acc_ref) = refs
    else:
        q_ref, k_ref, v_ref, mask_ref, o_ref, m_ref, l_ref, acc_ref = refs
    j = pl.program_id(1)  # kv block (innermost: scratch accumulates per b)
    nj = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    mask = mask_ref[0, :, 0]  # [block_s]

    # Static unroll over kv heads: K is small (1-16) and each iteration is
    # an independent [G, block_s] online-softmax update against the SAME
    # VMEM-resident block — the slab is streamed from HBM once per step.
    for ki in range(kv_heads):
        q = q_ref[0, ki].astype(jnp.float32)  # [G, D]
        k = k_ref[0, :, ki].astype(jnp.float32)  # [block_s, D]
        v = v_ref[0, :, ki].astype(jnp.float32)
        if quantized:
            # int8 cache: HBM streams 1-byte values; dequant happens here
            # in VMEM (the XLA path fuses the same multiply into its einsum)
            k = k * ks_ref[0, :, ki][:, None]
            v = v * vs_ref[0, :, ki][:, None]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [G, block_s]
        if softcap is not None:
            s = jnp.tanh(s / softcap) * softcap
        s = jnp.where(mask[None, :], s, NEG_INF)

        rows = slice(ki * group, (ki + 1) * group)
        m_prev = m_ref[rows]  # [G, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        # re-zero masked slots: exp(NEG_INF - m) underflows to 0 for any
        # real m, but a FULLY-masked row has m == NEG_INF and would get
        # p == 1 everywhere, silently averaging V over garbage slots
        p = jnp.where(mask[None, :], p, 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[rows] = l_ref[rows] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[rows] = acc_ref[rows] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_ref[rows] = m_new

    @pl.when(j == nj - 1)
    def _finalize():
        # A row with nothing visible (can't happen for real rows — the
        # current token is always valid) has l == 0 thanks to the p
        # re-zeroing above; emit zeros instead of dividing by zero.
        l = jnp.where(l_ref[:] == 0.0, 1.0, l_ref[:])
        o_ref[0] = (acc_ref[:] / l).reshape(o_ref.shape[1:]).astype(o_ref.dtype)


def select_block_s(
    s: int, kv_heads: int, head_dim: int, kv_itemsize: int,
    requested: int, quantized: bool,
) -> int:
    """Largest kv-block length that divides ``s``, is 8-aligned (Mosaic
    second-minor rule for the [B, S, 1] mask block), and keeps the
    double-buffered K/V(+scale) working set inside the VMEM budget.

    Falls back to a single whole-``s`` block for short unaligned caches
    (then the block equals the full dim, which Mosaic also accepts).
    Raises for caches that are both unaligned and too large — Generator
    sizes caches to multiples of 128 (generate.py) so real callers never
    hit that.
    """
    row_bytes = kv_heads * head_dim * kv_itemsize * 2  # K and V
    if quantized:
        row_bytes += kv_heads * 4 * 2  # f32 k/v scales
    cap = max(8, (_VMEM_BUDGET_BYTES // (2 * row_bytes)) // 8 * 8)
    best = 0
    # start aligned DOWN to 8 — an unaligned start would step through
    # exclusively unaligned candidates and miss every valid divisor
    for cand in range(min(requested, cap, s) // 8 * 8, 7, -8):
        if s % cand == 0:
            best = cand
            break
    if best:
        return best
    # same double-buffering factor as the cap path above
    if 2 * s * row_bytes <= _VMEM_BUDGET_BYTES:
        return s  # single block; block dim == full dim satisfies Mosaic
    raise ValueError(
        f"decode_attention: cache length {s} has no 8-aligned divisor and "
        f"is too large for a single VMEM block; size caches to a multiple "
        f"of 8 (Generator rounds capacities to 128)"
    )


@functools.partial(
    jax.jit,
    static_argnames=("scale", "logit_softcap", "block_s", "interpret"),
)
def decode_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mask: jnp.ndarray,
    *,
    k_scale: jnp.ndarray | None = None,
    v_scale: jnp.ndarray | None = None,
    scale: float,
    logit_softcap: float | None = None,
    block_s: int = 512,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """One-token GQA attention against the cache.

    q [B, 1, H, D], k/v [B, S, K, D], mask [B, S] bool (True = visible)
    → [B, 1, H, D].  Equivalent to ``gqa_attention(q, k, v, mask[:,None,:])``
    — verified against it in tests.

    int8 cache mode: pass k/v as int8 with ``k_scale``/``v_scale``
    [B, S, K] (cache.quantize_kv layout); the kernel streams 1-byte
    values from HBM and dequantizes in VMEM — the combination that would
    otherwise materialize full dequantized slabs per step.

    interpret=None auto-selects: compiled on TPU, interpreter elsewhere.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    quantized = k_scale is not None
    if (
        quantized != (k.dtype == jnp.int8)
        or quantized != (v.dtype == jnp.int8)
        or quantized != (v_scale is not None)
    ):
        raise ValueError(
            "int8 k AND v require both k_scale and v_scale (and vice "
            f"versa); got k={k.dtype}, v={v.dtype}, "
            f"k_scale={'set' if k_scale is not None else None}, "
            f"v_scale={'set' if v_scale is not None else None}"
        )
    b, one, h, d = q.shape
    assert one == 1, f"decode_attention is q_len=1 only, got {one}"
    _, s, kh, _ = k.shape
    g = h // kh
    out_dtype = q.dtype

    # ZERO-COPY contract: decode is HBM-bound on streaming the cache slab,
    # so the kernel reads K/V in their NATIVE [B, S, K, D] layout via 4-D
    # BlockSpecs whose trailing (K, D) dims are the FULL array dims — no
    # transpose/pad materialization of the slabs, and Mosaic's trailing-
    # dims alignment rule is satisfied for any K/D.  q's head split
    # [B,1,H,D]→[B,K,G,D] is a free reshape.
    qf = q.reshape(b, kh, g, d)  # [B, K, G, D]
    mask3 = mask[:, :, None]  # [B, S, 1]: trailing dims (block_s, 1)

    block_s = select_block_s(
        s, kh, d, jnp.dtype(k.dtype).itemsize, block_s, quantized
    )

    grid = (b, s // block_s)
    in_specs = [
        pl.BlockSpec((1, kh, g, d), lambda bi, j: (bi, 0, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_s, kh, d), lambda bi, j: (bi, j, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_s, kh, d), lambda bi, j: (bi, j, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_s, 1), lambda bi, j: (bi, j, 0),
                     memory_space=pltpu.VMEM),
    ]
    operands = [qf, k, v, mask3]
    if quantized:
        scale_spec = pl.BlockSpec(
            (1, block_s, kh), lambda bi, j: (bi, j, 0),
            memory_space=pltpu.VMEM,
        )
        in_specs += [scale_spec, scale_spec]
        operands += [k_scale, v_scale]
    out = pl.pallas_call(
        functools.partial(
            _decode_kernel, scale=scale, softcap=logit_softcap,
            quantized=quantized, kv_heads=kh, group=g,
        ),
        out_shape=jax.ShapeDtypeStruct((b, kh, g, d), out_dtype),
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, kh, g, d), lambda bi, j: (bi, 0, 0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((h, 1), jnp.float32),
            pltpu.VMEM((h, 1), jnp.float32),
            pltpu.VMEM((h, d), jnp.float32),
        ],
        interpret=interpret,
    )(*operands)

    return out.reshape(b, 1, h, d)
