"""Blockwise (flash) attention kernel for prefill.

The TPU-native answer to the reference's full-score-matrix attention
(``q@k.T`` materialized at llama3.2_model.py:467-469, then a custom CUDA
softmax over it): online-softmax over KV blocks with running (max, sum,
accumulator) state in VMEM — the [Sq, Skv] matrix never exists in HBM, so
long-sequence prefill is bandwidth-bound on K/V streaming only.

Supports the framework's full attention surface: GQA head grouping (each
query head reads kv head h // group), causal masking, sliding windows
(Gemma-2 local layers), and attention-logit softcapping.

Self-attention only (Sq == Skv, positions 0..S): the prefill path.  Decode
(q_len=1 against a long cache) stays on the XLA path where the score
"matrix" is a vector and fusion is already optimal.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float(jnp.finfo(jnp.float32).min)


def _kv_block_bounds(
    i, block_q: int, block_kv: int, window: int | None
):
    """Visible kv-block range [jmin, jmax] for q block ``i`` — pure
    grid-index arithmetic, shared by the kernel gate and the BlockSpec
    index maps (a kv block outside the range repeats the previous block
    index, so its DMA is elided entirely).

    Causal upper bound: first col ≤ the q block's last row.  Window lower
    bound: the block is visible iff its last col is within ``window`` of
    the q block's first row (the per-element mask finishes the job).
    """
    q_last = i * block_q + block_q - 1
    jmax = q_last // block_kv
    if window is None:
        return 0, jmax
    # smallest j with  i*block_q - (j*block_kv + block_kv - 1) < window
    jmin = (i * block_q - window - block_kv + 1) // block_kv + 1
    return jnp.maximum(jmin, 0), jmax


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref,
    *, scale: float, block_q: int, block_kv: int,
    softcap: float | None, window: int | None, seq_len: int,
):
    i = pl.program_id(1)  # q block
    j = pl.program_id(2)  # kv block step (offset into the visible range)
    nj = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    jmin, jmax = _kv_block_bounds(i, block_q, block_kv, window)
    q_start = i * block_q
    # the index maps clamp the fetched block to min(jmin + j, jmax);
    # steps past the visible range re-see block jmax and skip compute
    kv_start = jnp.minimum(jmin + j, jmax) * block_kv

    @pl.when(jmin + j <= jmax)
    def _work():
        # bf16 MXU operands with f32 accumulation (same contract as the
        # XLA path's einsums) — pre-casting to f32 ran the matmuls at the
        # MXU's f32 rate and cost the r4 bench 23% vs XLA at 8k prefill
        q = q_ref[0]  # [block_q, D]
        k = k_ref[0]  # [block_kv, D]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [block_q, block_kv]
        if softcap is not None:
            s = jnp.tanh(s / softcap) * softcap

        rows = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        cols = kv_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = (cols <= rows) & (cols < seq_len)
        if window is not None:
            mask &= (rows - cols) < window
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[:]  # [block_q, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = m_new

    @pl.when(j == nj - 1)
    def _finalize():
        # Every real row attends at least itself; padded rows (beyond
        # seq_len) have l == 0 — guard the division, their output is
        # sliced off by the wrapper.
        l = jnp.where(l_ref[:] == 0.0, 1.0, l_ref[:])
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "logit_softcap", "window", "block_q", "block_kv", "interpret"),
)
def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    scale: float,
    logit_softcap: float | None = None,
    window: int | None = None,
    block_q: int = 128,
    block_kv: int = 128,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Causal self-attention: q [B, S, H, D], k/v [B, S, K, D] → [B, S, H, D].

    Equivalent to ``ops.attention.gqa_attention`` with a causal(+window)
    mask over positions 0..S-1 — verified against it in tests; the XLA path
    remains the fallback (SURVEY §7 step 7: benchmark-gated).

    interpret=None auto-selects: compiled on TPU, interpreter elsewhere
    (CPU tests exercise the same kernel logic via the interpreter).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, s, h, d = q.shape
    _, _, kh, _ = k.shape
    g = h // kh
    out_dtype = q.dtype

    # [B, S, H, D] → [B*H, S, D]; kv → [B*K, S, D]
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * kh, s, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * kh, s, d)

    s_pad = (-s) % max(block_q, block_kv)
    if s_pad:
        qf = jnp.pad(qf, ((0, 0), (0, s_pad), (0, 0)))
        kf = jnp.pad(kf, ((0, 0), (0, s_pad), (0, 0)))
        vf = jnp.pad(vf, ((0, 0), (0, s_pad), (0, 0)))
    sp = s + s_pad

    grid = (b * h, sp // block_q, sp // block_kv)

    def q_map(bh, i, j):
        return (bh, i, 0)

    def kv_map(bh, i, j):
        # query head bh → batch bh//h, kv head (bh%h)//g.  The kv block
        # index clamps into the visible range for q block i: out-of-range
        # steps repeat an already-fetched block, so the causal upper
        # triangle (and, with a window, the stale lower band) is never
        # streamed from HBM — the XLA path always streams all of K/V.
        jmin, jmax = _kv_block_bounds(i, block_q, block_kv, window)
        return ((bh // h) * kh + (bh % h) // g, jnp.minimum(jmin + j, jmax), 0)

    kernel = functools.partial(
        _flash_kernel,
        scale=scale,
        block_q=block_q,
        block_kv=block_kv,
        softcap=logit_softcap,
        window=window,
        seq_len=s,
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b * h, sp, d), out_dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), q_map, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_kv, d), kv_map, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_kv, d), kv_map, memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), q_map, memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)

    if s_pad:
        out = out[:, :s, :]
    return out.reshape(b, h, s, d).transpose(0, 2, 1, 3)
