"""Pallas TPU kernels — the custom-kernel path.

The reference's one piece of native accelerator code is an inline CUDA C
softmax launched through ``cp.RawKernel`` (llama3.2_model.py:924-975).
Pallas is the TPU-native equivalent of that role: ``softmax`` reproduces the
fused-softmax kernel, and ``flash_attention`` is the kernel that actually
matters on TPU — blockwise online-softmax attention that never materializes
the [Sq, Skv] score matrix in HBM.

Both fall back to (or are verified against) the XLA path; kernels are
benchmark-gated, not load-bearing for correctness (SURVEY §7 step 7).
"""

from llm_np_cp_tpu.ops.pallas.softmax import softmax
from llm_np_cp_tpu.ops.pallas.flash_attention import flash_attention

__all__ = ["softmax", "flash_attention"]
