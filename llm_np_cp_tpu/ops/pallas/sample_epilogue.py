"""Fused sampling epilogue: final RMSNorm → lm_head → sample, streamed
over vocab tiles — the tick-tail fusion kernel (PAPERS.md: "LLM
Inference Acceleration via Efficient Operation Fusion").

The serve engine's XLA tail materializes full ``[rows, V]`` float32
logits in HBM (a 128k-vocab row is 512 KB, written once by the lm_head
einsum and read back by the sampler) even though a non-logprobs request
only ever consumes ONE token id per row.  This kernel collapses the
chain: each grid step streams one ``[*, block_v]`` lm_head tile through
VMEM, computes that tile's logits for every row (final RMSNorm applied
once into scratch on the first step), and folds them into a running
per-row sample state — the logits never exist outside VMEM.

Sampling: the streaming state is the greedy argmax (running best value
+ first-occurrence index, bit-identical to ``jnp.argmax`` over the full
logits row — strict-greater tile combining preserves first-max
tie-breaking, which softcap saturation and int8 weights do produce).
Greedy is the one sampler kind whose fused draw is exactly
token-identical to the XLA ``final_logits`` + ``Sampler`` oracle, so
the serve/offline gates select the fused path only for greedy samplers;
extending the stream to the stochastic kinds (temperature / top-p via
an in-kernel counter-based threefry reproducing ``jax.random``'s exact
bits, plus a streaming nucleus-threshold pass) is recorded ROADMAP
debt — the fallback path keeps serving them byte-identically meanwhile.

Numerics mirror the XLA chain op for op so greedy argmax parity is
exact: RMSNorm reduces in f32 and casts back to the activation dtype
(ops/norms.rms_norm), the lm_head dot accumulates f32
(quant_einsum's ``preferred_element_type``), int8 weights rescale the
f32 product per vocab column, and the softcap runs on the f32 logits.

Weight layouts (models/transformer.epilogue_params hands them over):
tied heads stream the embedding table ``[V, H]`` (block ``(block_v,
H)``), untied heads ``[H, V]`` (block ``(H, block_v)``); int8 heads
(quant.py payload ``"q"``) stream the 1-byte payload with their
``[1, V]`` f32 scales riding along.  Benchmark-gated like every kernel
here: probe ``sample_epilogue[_int8]`` in ops/pallas/support.py, XLA
fallback everywhere (Mosaic-compiling this kernel on hardware is
recorded live-TPU debt).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float(jnp.finfo(jnp.float32).min)

# Default vocab-tile width: a multiple of 128 (Mosaic lane tile; also
# satisfies the (32, 128) int8 sublane tile on the tied layout's
# second-minor axis) small enough that a double-buffered bf16 tile of a
# 2k-hidden model stays ~2 MiB in VMEM.
BLOCK_V = 512


def _epilogue_kernel(
    *refs,
    tied: bool,
    quantized: bool,
    eps: float,
    unit_offset: bool,
    softcap: float | None,
    block_v: int,
    vocab: int,
):
    if quantized:
        x_ref, g_ref, w_ref, s_ref, o_ref, xn_ref, bv_ref, bi_ref = refs
    else:
        x_ref, g_ref, w_ref, o_ref, xn_ref, bv_ref, bi_ref = refs
    j = pl.program_id(0)
    nj = pl.num_programs(0)

    @pl.when(j == 0)
    def _init():
        # final RMSNorm once per row into scratch, mirroring
        # ops/norms.rms_norm bit for bit: f32 reduction + rsqrt, weight
        # (+1 under unit offset) applied in f32, cast back to the
        # activation dtype — the dtype the lm_head dot consumes
        xf = x_ref[:].astype(jnp.float32)
        var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
        normed = xf * lax.rsqrt(var + eps)
        w = g_ref[:].astype(jnp.float32)  # [1, H]
        if unit_offset:
            w = w + 1.0
        xn_ref[:] = (normed * w).astype(xn_ref.dtype)
        bv_ref[:] = jnp.full_like(bv_ref, NEG_INF)
        bi_ref[:] = jnp.zeros_like(bi_ref)

    xn = xn_ref[:]  # [N, H]
    wb = w_ref[:]
    if quantized:
        wb = wb.astype(xn.dtype)
    # one vocab tile's logits for every row, f32 accumulation — the
    # same contraction quant_einsum("...h,vh->...v" / "...h,hv->...v")
    # traces, so values (and therefore argmax ties) match the oracle
    if tied:
        s = jax.lax.dot_general(
            xn, wb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [N, block_v]
    else:
        s = jax.lax.dot_general(
            xn, wb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
    if quantized:
        s = s * s_ref[:]  # [1, block_v] f32 per-column scales
    if softcap is not None:
        s = jnp.tanh(s / softcap) * softcap
    # mask the tail tile's fake columns (rank-2 iota: Mosaic rejects
    # rank-1 iota on TPU)
    col = j * block_v + jax.lax.broadcasted_iota(
        jnp.int32, s.shape, 1
    )
    s = jnp.where(col < vocab, s, NEG_INF)

    # streaming argmax: within-tile argmax takes the FIRST max, and the
    # strict-greater combine keeps the earlier tile on cross-tile ties —
    # exactly jnp.argmax's first-occurrence rule over the full row
    tile_best = jnp.max(s, axis=-1, keepdims=True)  # [N, 1]
    tile_idx = (
        j * block_v + jnp.argmax(s, axis=-1, keepdims=True)
    ).astype(jnp.int32)
    better = tile_best > bv_ref[:]
    bv_ref[:] = jnp.where(better, tile_best, bv_ref[:])
    bi_ref[:] = jnp.where(better, tile_idx, bi_ref[:])

    @pl.when(j == nj - 1)
    def _emit():
        o_ref[:] = bi_ref[:]


@functools.partial(
    jax.jit,
    static_argnames=(
        "tied", "eps", "unit_offset", "logit_softcap", "block_v",
        "interpret",
    ),
)
def sample_epilogue(
    x: jnp.ndarray,
    gamma: jnp.ndarray,
    w: jnp.ndarray,
    *,
    w_scale: jnp.ndarray | None = None,
    tied: bool,
    eps: float,
    unit_offset: bool = False,
    logit_softcap: float | None = None,
    block_v: int = BLOCK_V,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Greedy-sample the next token for each row of ``x`` without ever
    materializing the logits.

    x [N, H] — final-layer hidden states (pre final-norm; one row per
    sample slot).  gamma [H] — the final RMSNorm weight.  w — the
    lm-head weight: ``[V, H]`` when ``tied`` (the embedding table),
    ``[H, V]`` otherwise; int8 payloads ride with ``w_scale`` [1, V]
    f32 per-vocab-column scales (quant.py's ``"q"`` mode).  → [N] int32
    token ids, bit-identical to ``Sampler(kind="greedy")`` over
    ``final_logits`` (models/transformer.py) — pinned in tests.

    Rows are padded to the f32 sublane tile internally; pad rows are
    zeros, normalize to zeros, and their draw is sliced off.
    interpret=None auto-selects like the other kernels here.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    quantized = w_scale is not None
    if quantized != (w.dtype == jnp.int8):
        raise ValueError(
            "int8 lm-head payloads require w_scale (and vice versa); "
            f"got w={w.dtype}, "
            f"w_scale={'set' if w_scale is not None else None}"
        )
    n, h = x.shape
    v = w.shape[0] if tied else w.shape[1]
    if (w.shape[1] if tied else w.shape[0]) != h:
        raise ValueError(
            f"lm-head weight {w.shape} does not match hidden size {h} "
            f"(tied={tied})"
        )
    if block_v % 128:
        raise ValueError(f"block_v must be a multiple of 128, got {block_v}")
    n8 = -(-n // 8) * 8
    if n8 != n:
        x = jnp.pad(x, [(0, n8 - n), (0, 0)])
    bv = v if v <= block_v else block_v
    nv = -(-v // bv)

    if tied:
        w_spec = pl.BlockSpec((bv, h), lambda j: (j, 0),
                              memory_space=pltpu.VMEM)
    else:
        w_spec = pl.BlockSpec((h, bv), lambda j: (0, j),
                              memory_space=pltpu.VMEM)
    in_specs = [
        pl.BlockSpec((n8, h), lambda j: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((1, h), lambda j: (0, 0), memory_space=pltpu.VMEM),
        w_spec,
    ]
    operands = [x, gamma.reshape(1, h), w]
    if quantized:
        in_specs.append(
            pl.BlockSpec((1, bv), lambda j: (0, j),
                         memory_space=pltpu.VMEM)
        )
        operands.append(w_scale.astype(jnp.float32))
    out = pl.pallas_call(
        functools.partial(
            _epilogue_kernel, tied=tied, quantized=quantized, eps=eps,
            unit_offset=unit_offset, softcap=logit_softcap, block_v=bv,
            vocab=v,
        ),
        out_shape=jax.ShapeDtypeStruct((n8, 1), jnp.int32),
        grid=(nv,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((n8, 1), lambda j: (0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((n8, h), x.dtype),
            pltpu.VMEM((n8, 1), jnp.float32),
            pltpu.VMEM((n8, 1), jnp.int32),
        ],
        interpret=interpret,
    )(*operands)
    return out[:n, 0]
