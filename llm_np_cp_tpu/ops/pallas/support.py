"""Mosaic compile-support probes for the Pallas kernels.

The kernels auto-select the interpreter off-TPU, so CPU tests always
pass — but whether Mosaic accepts a kernel's BlockSpecs is only known on
real hardware at compile time (r3 postmortem: the decode kernel's
original layout passed every interpret-mode test and was rejected by
Mosaic at first hardware compile).  These probes compile each kernel
once at tiny shapes on the live backend and cache the verdict, so
selection sites (Generator, bench) can downgrade to the XLA path with a
warning instead of dying at first dispatch.

The reference's custom kernel is launched unconditionally at import
(/root/reference/llama3.2_model.py:977-980) and simply crashes the
process if the toolchain is broken; gating is the TPU-native upgrade.
"""

from __future__ import annotations

import functools
import logging

import jax
import jax.numpy as jnp
import numpy as np

log = logging.getLogger("llm_np_cp_tpu")

# test hook: force every probe to report failure (monkeypatched in tests)
_FORCE_FAIL = False

# Runtime degradation ledger: kernels that PASSED their startup probe but
# then faulted at dispatch mid-traffic (serve engine runtime fallback).
# A faulted kernel stays disabled for the whole process — including
# supervisor engine rebuilds — so one bad dispatch becomes one fallback,
# not a crash loop.  kernel name → reason string.
_RUNTIME_DISABLED: dict[str, str] = {}


def disable_kernel(kernel: str, reason: str) -> None:
    """Record a dispatch-time fault for ``kernel``: every subsequent
    ``kernel_error``/``gate_attn_impl`` call reports it unavailable."""
    _RUNTIME_DISABLED.setdefault(kernel, f"faulted at dispatch: {reason}")
    log.warning(
        "Pallas kernel %s disabled for this process (%s)", kernel, reason
    )


@functools.lru_cache(maxsize=None)
def _probe(kernel: str, backend: str) -> str | None:
    """Compile+run `kernel` at tiny shapes on `backend`.

    Returns None on success, else the error string.  Cached per process;
    off-TPU backends return None without compiling (the kernels run the
    interpreter there, which always works).
    """
    if _FORCE_FAIL:
        return "forced failure (test hook)"
    if backend != "tpu":
        return None
    rng = np.random.default_rng(0)
    try:
        if kernel == "softmax":
            from llm_np_cp_tpu.ops.pallas.softmax import softmax

            x = jnp.asarray(rng.standard_normal((8, 256)), jnp.float32)
            np.asarray(softmax(x, interpret=False))
        elif kernel == "flash_attention":
            from llm_np_cp_tpu.ops.pallas.flash_attention import flash_attention

            q = jnp.asarray(rng.standard_normal((1, 128, 2, 64)), jnp.bfloat16)
            k = jnp.asarray(rng.standard_normal((1, 128, 1, 64)), jnp.bfloat16)
            np.asarray(flash_attention(q, k, k, scale=0.125, interpret=False))
        elif kernel in ("decode_attention", "decode_attention_int8"):
            from llm_np_cp_tpu.ops.pallas.decode_attention import decode_attention

            # GQA shape representative of real models: kh>1 exercises the
            # kernel's static kv-head unroll, and g=4 puts the scratch row
            # slices at non-8-aligned sublane offsets (ki*g = 0, 4) — the
            # layout class only a hardware compile validates
            b, s, khd = 1, 128, 64
            q = jnp.asarray(rng.standard_normal((b, 1, 8, khd)), jnp.bfloat16)
            kv = jnp.asarray(rng.standard_normal((b, s, 2, khd)), jnp.bfloat16)
            mask = jnp.ones((b, s), bool)
            if kernel.endswith("int8"):
                from llm_np_cp_tpu.cache import quantize_kv

                kq, ks = quantize_kv(kv)
                np.asarray(decode_attention(
                    q, kq, kq, mask, k_scale=ks, v_scale=ks, scale=0.125,
                    block_s=64, interpret=False,
                ))
            else:
                np.asarray(decode_attention(
                    q, kv, kv, mask, scale=0.125, block_s=64, interpret=False,
                ))
        elif kernel in ("paged_decode_attention", "paged_decode_attention_int8"):
            from llm_np_cp_tpu.ops.pallas.decode_attention import (
                paged_decode_attention,
            )

            # serving-pool shapes: 4 blocks of 32 slots, 2-row batch with
            # block tables permuting the pool — the scalar-prefetch index
            # map is the layout class only a hardware compile validates;
            # row 1's pad spans a whole block (start = 1) so the
            # leading-block-skip path compiles too
            b, nbp, bs, khd = 2, 4, 32, 64
            q = jnp.asarray(rng.standard_normal((b, 1, 8, khd)), jnp.bfloat16)
            pages = jnp.asarray(
                rng.standard_normal((nbp, bs, 2, khd)), jnp.bfloat16
            )
            tables = jnp.asarray([[2, 1], [3, 0]], jnp.int32)
            lengths = jnp.asarray([40, 63], jnp.int32)
            pads = jnp.asarray([0, 35], jnp.int32)
            kwargs = {}
            if kernel.endswith("int8"):
                from llm_np_cp_tpu.cache import quantize_kv

                pages, scales = quantize_kv(pages)
                kwargs = dict(k_scale=scales, v_scale=scales)
            np.asarray(paged_decode_attention(
                q, pages, pages, tables, lengths, pads, scale=0.125,
                interpret=False, **kwargs,
            ))
        elif kernel in ("sample_epilogue", "sample_epilogue_int8"):
            from llm_np_cp_tpu.ops.pallas.sample_epilogue import (
                sample_epilogue,
            )

            # both head layouts at a multi-tile vocab with a ragged tail
            # (300 = 2*128 + 44): the streamed lm-head BlockSpecs + the
            # argmax/scratch layout class only a hardware compile
            # validates.  5 rows exercise the sublane pad too.
            n, h, v = 5, 64, 300
            x = jnp.asarray(rng.standard_normal((n, h)), jnp.bfloat16)
            gamma = jnp.asarray(rng.standard_normal((h,)), jnp.bfloat16)
            tied_w = jnp.asarray(rng.standard_normal((v, h)), jnp.bfloat16)
            untied_w = jnp.asarray(
                rng.standard_normal((h, v)), jnp.bfloat16
            )
            kwargs = {}
            if kernel.endswith("int8"):
                from llm_np_cp_tpu.quant import quantize_array

                qt = quantize_array(tied_w, axis=-1)
                qu = quantize_array(untied_w, axis=-2)
                tied_w, untied_w = qt["q"], qu["q"]
                tied_kwargs = dict(w_scale=qt["s"].reshape(1, -1))
                untied_kwargs = dict(w_scale=qu["s"].reshape(1, -1))
            else:
                tied_kwargs = untied_kwargs = {}
            np.asarray(sample_epilogue(
                x, gamma, tied_w, tied=True, eps=1e-6, block_v=128,
                interpret=False, **tied_kwargs,
            ))
            np.asarray(sample_epilogue(
                x, gamma, untied_w, tied=False, eps=1e-6,
                logit_softcap=30.0, unit_offset=True, block_v=128,
                interpret=False, **untied_kwargs,
            ))
        elif kernel in ("ragged_paged_attention", "ragged_paged_attention_int8"):
            from llm_np_cp_tpu.ops.pallas.decode_attention import (
                RAGGED_Q_TILE,
                ragged_paged_attention,
            )

            # a representative mixed tick: one 2-tile prefill segment
            # (ragged tail), one decode tile, one dead padding tile —
            # the tile-metadata scalar-prefetch + q-tile layout class
            # only a hardware compile validates
            nbp, bs, khd = 6, 32, 64
            qt = RAGGED_Q_TILE
            t = 4 * qt
            q = jnp.asarray(rng.standard_normal((t, 8, khd)), jnp.bfloat16)
            pages = jnp.asarray(
                rng.standard_normal((nbp, bs, 2, khd)), jnp.bfloat16
            )
            tables = jnp.asarray([[2, 1, 4], [3, 5, 0]], jnp.int32)
            tile_row = jnp.asarray([0, 0, 1, 0], jnp.int32)
            tile_qpos0 = jnp.asarray([5, 13, 40, 0], jnp.int32)
            tile_qlen = jnp.asarray([8, 4, 1, 0], jnp.int32)
            pads = jnp.asarray([5, 33], jnp.int32)
            kwargs = {}
            if kernel.endswith("int8"):
                from llm_np_cp_tpu.cache import quantize_kv

                pages, scales = quantize_kv(pages)
                kwargs = dict(k_scale=scales, v_scale=scales)
            np.asarray(ragged_paged_attention(
                q, pages, pages, tables, tile_row, tile_qpos0, tile_qlen,
                pads, jnp.int32(1 << 30), scale=0.125, interpret=False,
                **kwargs,
            ))
        else:
            raise ValueError(f"unknown kernel {kernel!r}")
    except Exception as e:  # noqa: BLE001 — any compile/runtime error gates
        return f"{type(e).__name__}: {e}"
    return None


def paged_kernel_name(int8_cache: bool) -> str:
    """Probe/kernel name for the block-table-native decode kernel — THE
    one int8-gating rule, shared by ``gate_attn_impl`` and the CLI's
    pre-build check so the two can't drift."""
    return (
        "paged_decode_attention_int8" if int8_cache
        else "paged_decode_attention"
    )


def ragged_kernel_name(int8_cache: bool) -> str:
    """Probe/kernel name for the mixed prefill+decode ragged kernel
    (the unified-tick dispatch) — same one-rule discipline as
    ``paged_kernel_name``, shared by the engine's ``mixed_step`` gate
    and the CLI's pre-build check."""
    return (
        "ragged_paged_attention_int8" if int8_cache
        else "ragged_paged_attention"
    )


def epilogue_kernel_name(int8_head: bool) -> str:
    """Probe/kernel name for the fused sampling epilogue (final norm →
    lm_head → greedy sample over vocab tiles) — same one-rule
    discipline as ``paged_kernel_name``, shared by the serve engine's
    epilogue gate and the offline Generator so the two can't drift.
    ``int8_head``: the lm-head weight is a quant.py int8 payload."""
    return "sample_epilogue_int8" if int8_head else "sample_epilogue"


def kernel_error(kernel: str) -> str | None:
    """None if `kernel` compiles on the current default backend and has
    not been disabled by a dispatch-time fault (``disable_kernel``)."""
    disabled = _RUNTIME_DISABLED.get(kernel)
    if disabled is not None:
        return disabled
    return _probe(kernel, jax.default_backend())


def kernel_available(kernel: str) -> bool:
    return kernel_error(kernel) is None


def gate_attn_impl(impl: str, *, int8_cache: bool = False) -> str:
    """Downgrade a Pallas attn impl to 'xla' if Mosaic rejects it.

    Logs once per process per kernel (lru_cache on _probe); returns the
    impl to actually use.
    """
    kernel = {
        "flash": "flash_attention",
        "ring": None,  # ring uses the XLA path per shard; nothing to gate
        "flash_decode": (
            "decode_attention_int8" if int8_cache else "decode_attention"
        ),
        "paged": paged_kernel_name(int8_cache),
        "xla": None,
    }.get(impl)
    if kernel is None:
        return impl
    err = kernel_error(kernel)
    if err is None:
        return impl
    log.warning(
        "Pallas kernel %s failed to compile on %s (%s); falling back to "
        "the XLA attention path",
        kernel, jax.default_backend(), err,
    )
    return "xla"
