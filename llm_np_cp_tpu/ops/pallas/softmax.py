"""Fused stable softmax kernel.

Role-equivalent of the reference's CUDA ``softmax_kernel``
(llama3.2_model.py:924-975): max-subtracted softmax over the last axis,
fused in one pass over on-chip memory.  The reference launches one CUDA
thread per *element*, each rescanning the whole axis; here one grid step
owns a block of rows resident in VMEM and the VPU does the row reductions.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _softmax_kernel(x_ref, o_ref):
    x = x_ref[:].astype(jnp.float32)
    m = jnp.max(x, axis=-1, keepdims=True)
    e = jnp.exp(x - m)
    o_ref[:] = (e / jnp.sum(e, axis=-1, keepdims=True)).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block_rows", "interpret"))
def softmax(
    x: jnp.ndarray, *, block_rows: int = 8, interpret: bool | None = None
) -> jnp.ndarray:
    """Softmax over the last axis via a Pallas kernel.

    Leading axes are flattened to rows; ``block_rows`` rows are processed
    per grid step (the whole axis must fit in VMEM — true for vocab-sized
    axes: 8 rows × 128256 f32 ≈ 4 MB).

    interpret=None auto-selects: compiled on TPU, interpreter elsewhere.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    orig_shape = x.shape
    axis = orig_shape[-1]
    rows = 1
    for d in orig_shape[:-1]:
        rows *= d
    x2 = x.reshape(rows, axis)

    pad = (-rows) % block_rows
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))

    out = pl.pallas_call(
        _softmax_kernel,
        out_shape=jax.ShapeDtypeStruct(x2.shape, x.dtype),
        grid=(x2.shape[0] // block_rows,),
        in_specs=[
            pl.BlockSpec(
                (block_rows, axis), lambda i: (i, 0), memory_space=pltpu.VMEM
            )
        ],
        out_specs=pl.BlockSpec(
            (block_rows, axis), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        interpret=interpret,
    )(x2)
    if pad:
        out = out[:rows]
    return out.reshape(orig_shape)
