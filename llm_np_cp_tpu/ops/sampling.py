"""Token sampling (the reference's L4 layer, SURVEY §2.8).

Reference surface: min-p sampling (live path, llama3.2_model.py:1000-1013),
greedy argmax (commented alternative, :895-896), and a pure-Python CDF walk
(``sample``, :828-841).  All are reproduced here as pure JAX functions over
a ``[..., vocab]`` logits array; the RNG is ``jax.random`` (the reference
draws through ``torch.multinomial`` — identical distributions, different
streams, so token-level parity tests pin greedy, SURVEY §4c).

Beyond the reference: temperature, top-k, and top-p, so the framework covers
the standard sampler set users expect.

Numerics note: the reference's live sampling softmax is the *unstable*
``exp/sum`` (``softmax2``, llama3.2_model.py:991-994).  Min-p thresholds are
invariant to the max-shift (both p and max(p) scale by the same factor), so
the stable softmax used here is semantically identical and never overflows.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

NEG_INF = float(jnp.finfo(jnp.float32).min)


def greedy(logits: jnp.ndarray) -> jnp.ndarray:
    """Argmax over the vocab axis → int32 token ids."""
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def min_p_mask(logits: jnp.ndarray, p_base: float) -> jnp.ndarray:
    """Mask logits of tokens with prob < max_prob * p_base to -inf.

    Equivalent to the reference's keep/renormalize (llama3.2_model.py:
    1004-1008): ``categorical`` over the masked logits IS sampling from the
    renormalized kept distribution.
    """
    logits = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    keep = logp >= (jnp.max(logp, axis=-1, keepdims=True) + jnp.log(p_base))
    return jnp.where(keep, logits, NEG_INF)


def min_p(key: jax.Array, logits: jnp.ndarray, p_base: float = 0.1) -> jnp.ndarray:
    return jax.random.categorical(key, min_p_mask(logits, p_base), axis=-1).astype(
        jnp.int32
    )


def top_k_mask(logits: jnp.ndarray, k: int) -> jnp.ndarray:
    k = min(max(k, 1), logits.shape[-1])  # HF-style clamp: k=0 / k>V are user input
    kth = jnp.sort(logits, axis=-1)[..., -k][..., None]
    return jnp.where(logits >= kth, logits, NEG_INF)


def top_p_mask(logits: jnp.ndarray, p: float) -> jnp.ndarray:
    """Nucleus: keep the smallest prefix of the sorted distribution with
    cumulative prob >= p (the top token always survives)."""
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # token i is kept if the cumulative mass *before* it is < p; the top
    # token is forced alive so p<=0 (user input) degrades to greedy
    # instead of masking everything
    keep_sorted = ((cum - probs) < p).at[..., 0].set(True)
    threshold = jnp.min(
        jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1, keepdims=True
    )
    return jnp.where(logits >= threshold, logits, NEG_INF)


def sample_cdf(key: jax.Array, logits: jnp.ndarray) -> jnp.ndarray:
    """Inverse-CDF draw — the vectorized form of the reference's Python
    probability walk (``sample``, llama3.2_model.py:828-841)."""
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    cdf = jnp.cumsum(probs, axis=-1)
    u = jax.random.uniform(key, logits.shape[:-1] + (1,), dtype=jnp.float32)
    return jnp.sum(cdf < u, axis=-1).astype(jnp.int32)


@dataclasses.dataclass(frozen=True)
class Sampler:
    """Static sampler spec; ``__call__`` is traceable and closes over no state.

    kind: "greedy" | "min_p" | "cdf" | "top_k" | "top_p"
    """

    kind: str = "greedy"
    temperature: float = 1.0
    p_base: float = 0.1  # min-p threshold (reference default, llama3.2_model.py:1000)
    top_k: int = 50
    top_p: float = 0.9

    def __call__(self, key: jax.Array, logits: jnp.ndarray) -> jnp.ndarray:
        logits = logits.astype(jnp.float32)
        if self.kind == "greedy":
            return greedy(logits)
        if self.kind == "cdf":
            if self.temperature != 1.0:
                logits = logits / self.temperature
            return sample_cdf(key, logits)
        # min_p / top_k / top_p: sampling from the masked logits IS the
        # filtered distribution — one dispatch chain, shared with
        # speculative decoding via filtered_logits
        return jax.random.categorical(
            key, self.filtered_logits(logits), axis=-1
        ).astype(jnp.int32)

    def filtered_logits(self, logits: jnp.ndarray) -> jnp.ndarray:
        """Post-filter logits whose softmax is this sampler's effective
        token distribution (``categorical(filtered_logits)`` ≡ __call__ in
        distribution).  Greedy degenerates to a one-hot on ``argmax`` —
        the FIRST maximal index, matching ``greedy()``'s tie-breaking so
        speculative greedy stays byte-identical even when logits tie
        (softcap saturation and int8 weights do produce exact ties).
        Speculative decoding consumes these for both draft and target.
        """
        logits = logits.astype(jnp.float32)
        if self.kind == "greedy":
            idx = jnp.argmax(logits, axis=-1, keepdims=True)
            iota = jnp.arange(logits.shape[-1])
            return jnp.where(iota == idx, 0.0, NEG_INF)
        if self.temperature != 1.0:
            logits = logits / self.temperature
        if self.kind == "min_p":
            return min_p_mask(logits, self.p_base)
        if self.kind == "cdf":
            return logits
        if self.kind == "top_k":
            return top_k_mask(logits, self.top_k)
        if self.kind == "top_p":
            return top_p_mask(logits, self.top_p)
        raise ValueError(f"unknown sampler kind: {self.kind}")
