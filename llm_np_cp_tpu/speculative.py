"""Speculative decoding: draft-and-verify autoregressive generation.

Framework extension (the reference decodes strictly one token per forward,
llama3.2_model.py:865-902).  A cheap *draft* model proposes γ tokens
autoregressively; the *target* model scores all of them in ONE forward
(prefill-shaped, MXU-friendly); accepted prefixes keep the target's exact
output distribution via the Leviathan et al. accept/resample rule:

    accept dᵢ with prob min(1, p(dᵢ)/q(dᵢ));
    on first rejection resample from norm(max(p − q, 0));
    if all γ accepted, sample a bonus token from p — so every round emits
    between 1 and γ+1 tokens and the sampled distribution is *identical*
    to decoding with the target alone (greedy: byte-identical output).

TPU-native shape: one jitted ``spec_round`` per (γ, sampler) — the draft
loop is a ``lax.scan``, verification is a single γ+1-token forward, and
rejected tokens are rolled back with ``cache.truncate`` (an O(1) bitmap
mask — the preallocated cache never moves).  p and q are the *filtered*
sampler distributions (``Sampler.filtered_logits``), so min-p/top-k/top-p
speculation is exact too, not just plain-softmax sampling.

The default draft is the int8-quantized target (quant.py) — "self
speculation": no second checkpoint, ~2× cheaper per draft step, and
high acceptance because the quantized model rarely disagrees with bf16.
A genuinely smaller draft model can be passed explicitly.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from llm_np_cp_tpu.cache import KVCache, truncate
from llm_np_cp_tpu.config import ModelConfig
from llm_np_cp_tpu.generate import _check_capacity, make_prefill_fn
from llm_np_cp_tpu.models.transformer import forward
from llm_np_cp_tpu.ops.sampling import Sampler

Params = dict[str, Any]


@dataclasses.dataclass
class SpecResult:
    tokens: np.ndarray  # [num_generated]
    ttft_s: float
    decode_tokens_per_s: float
    num_generated: int
    rounds: int
    acceptance_rate: float  # accepted draft tokens / proposed draft tokens
    tokens_per_round: float


def _spec_round_core(
    draft_params: Params,
    target_params: Params,
    t0: jnp.ndarray,
    dcache: KVCache,
    tcache: KVCache,
    key: jax.Array,
    *,
    draft_config: ModelConfig,
    target_config: ModelConfig,
    gamma: int,
    sampler: Sampler,
    draft_sampler: Sampler,
):
    """Traced body of one speculative round (batch 1) — see module doc."""
    kd, ku, kc = jax.random.split(key, 3)
    t_base = tcache.length
    d_base = dcache.length

    # --- draft: γ+1 steps (the extra step's proposal is discarded but
    # leaves the draft cache covering every verified input, so the
    # post-round rollback target base+n+1 always exists)
    def dstep(carry, k):
        tok, dc = carry
        logits, dc = forward(
            draft_params, tok[:, None], draft_config, dc, logits_last_only=True
        )
        fl = draft_sampler.filtered_logits(logits[:, -1])  # [1, V]
        nxt = jax.random.categorical(k, fl, axis=-1).astype(jnp.int32)
        return (nxt, dc), (nxt[0], jax.nn.softmax(fl[0], axis=-1))

    dkeys = jax.random.split(kd, gamma + 1)
    (_, dcache2), (drafts, qprobs) = lax.scan(dstep, (t0, dcache), dkeys)
    d = drafts[:gamma]  # proposals d_1..d_γ

    # --- target: verify all proposals in one forward
    inp = jnp.concatenate([t0, d])[None, :]  # [1, γ+1]
    tlogits, tcache2 = forward(target_params, inp, target_config, tcache)
    p = jax.nn.softmax(sampler.filtered_logits(tlogits[0]), axis=-1)  # [γ+1, V]

    # --- accept/reject (multiplied form avoids div-by-zero; q(d) > 0
    # by construction since d was sampled from q)
    idx = jnp.arange(gamma)
    p_d = p[idx, d]
    q_d = qprobs[idx, d]
    u = jax.random.uniform(ku, (gamma,), dtype=jnp.float32)
    accept = u * q_d < p_d
    n = jnp.where(jnp.all(accept), gamma, jnp.argmin(accept))

    # --- correction (n < γ: residual norm(max(p−q, 0))) or bonus
    # (n == γ: plain p) — unified by a zero row AT position γ (qprobs has
    # γ+1 rows; its last row is the discarded extra draft step's
    # distribution and must NOT leak into the bonus sample)
    q_pad = jnp.concatenate(
        [qprobs[:gamma], jnp.zeros((1,) + qprobs.shape[1:])]
    )
    residual = jnp.maximum(p[n] - q_pad[n], 0.0)
    total = jnp.sum(residual)
    dist = jnp.where(total > 0, residual / jnp.maximum(total, 1e-38), p[n])
    c = jax.random.categorical(kc, jnp.log(dist + 1e-38), axis=-1).astype(jnp.int32)

    emitted = jnp.concatenate([d, jnp.zeros((1,), jnp.int32)]).at[n].set(c)
    count = n + 1

    # --- roll both caches back to the accepted inputs t0..d_n
    tcache2 = truncate(tcache2, t_base + count)
    dcache2 = truncate(dcache2, d_base + count)
    return emitted, count, dcache2, tcache2, c[None]


def make_spec_round_fn(
    draft_config: ModelConfig,
    target_config: ModelConfig,
    gamma: int,
    sampler: Sampler,
    draft_sampler: Sampler | None = None,
):
    """One jitted speculative round (granular API; one dispatch per round).

    (draft_params, target_params, t0 [1], dcache, tcache, key) →
    (emitted [γ+1] (only the first ``count`` are real), count, dcache,
    tcache, next_t0 [1]).
    """
    from functools import partial

    return jax.jit(
        partial(
            _spec_round_core,
            draft_config=draft_config,
            target_config=target_config,
            gamma=gamma,
            sampler=sampler,
            draft_sampler=draft_sampler or sampler,
        )
    )


def make_spec_decode_fn(
    draft_config: ModelConfig,
    target_config: ModelConfig,
    gamma: int,
    sampler: Sampler,
    draft_sampler: Sampler | None = None,
    stop_tokens: tuple[int, ...] = (),
):
    """The fused loop: ALL speculative rounds in one ``lax.while_loop`` —
    a single device dispatch for the whole generation (per-round host
    sync costs a full transport RTT on a tunneled chip, same reason
    generate.py fuses its decode scan).

    (draft_params, target_params, t0 [1], dcache, tcache, key, max_new) →
    (buf [max_new+γ+1] (first ``total`` real, t0 included), total,
    rounds, accepted, dcache, tcache).
    """
    from functools import partial

    draft_sampler_ = draft_sampler or sampler
    stops = jnp.asarray(stop_tokens, dtype=jnp.int32) if stop_tokens else None

    @partial(jax.jit, static_argnums=(6,))
    def spec_decode(
        draft_params: Params,
        target_params: Params,
        t0: jnp.ndarray,
        dcache: KVCache,
        tcache: KVCache,
        key: jax.Array,
        max_new: int,
    ):
        buf = jnp.zeros((max_new + gamma + 1,), jnp.int32).at[0].set(t0[0])
        done0 = (
            jnp.any(t0[0] == stops) if stops is not None else jnp.array(False)
        )
        state = (
            jnp.ones((), jnp.int32),  # total emitted (t0 included)
            done0,
            t0,
            dcache,
            tcache,
            key,
            buf,
            jnp.zeros((), jnp.int32),  # rounds
            jnp.zeros((), jnp.int32),  # accepted draft tokens
        )

        def cond(state):
            total, done = state[0], state[1]
            return (total < max_new) & ~done

        def body(state):
            total, done, t, dcache, tcache, key, buf, rounds, accepted = state
            key, kr = jax.random.split(key)
            emitted, count, dcache, tcache, t = _spec_round_core(
                draft_params, target_params, t, dcache, tcache, kr,
                draft_config=draft_config, target_config=target_config,
                gamma=gamma, sampler=sampler, draft_sampler=draft_sampler_,
            )
            # write the whole γ+1 window; slots past `count` are garbage the
            # next round overwrites (buf is oversized by γ+1 for the tail)
            buf = lax.dynamic_update_slice(buf, emitted, (total,))
            if stops is not None:
                real = jnp.arange(gamma + 1) < count
                done = done | jnp.any(
                    real[:, None] & (emitted[:, None] == stops[None, :])
                )
            return (
                total + count, done, t, dcache, tcache, key, buf,
                rounds + 1, accepted + count - 1,
            )

        total, _, _, dcache, tcache, _, buf, rounds, accepted = lax.while_loop(
            cond, body, state
        )
        return buf, total, rounds, accepted, dcache, tcache

    return spec_decode


class SpeculativeGenerator:
    """Owns the jitted prefill + spec-round programs (batch size 1).

    draft defaults to the int8-quantized target params (self-speculation);
    pass ``draft_params``/``draft_config`` for a separate small model
    (they must share the tokenizer/vocab).
    """

    def __init__(
        self,
        params: Params,
        config: ModelConfig,
        *,
        draft_params: Params | None = None,
        draft_config: ModelConfig | None = None,
        gamma: int = 4,
        sampler: Sampler | None = None,
        draft_sampler: Sampler | None = None,
        cache_dtype: jnp.dtype = jnp.bfloat16,
    ) -> None:
        if draft_params is None:
            from llm_np_cp_tpu.quant import is_quantized, quantize_params

            if is_quantized(params["layers"].get("q_proj")):
                # target already int8 — nothing cheaper to derive; a
                # perfect draft (p == q) still pipelines γ+1 tokens/round
                draft_params = params
            else:
                draft_params = quantize_params(params)
        self.params = params
        self.config = config
        self.draft_params = draft_params
        self.draft_config = draft_config or config
        self.gamma = gamma
        self.sampler = sampler or Sampler()
        self._prefill_t = make_prefill_fn(config, self.sampler)
        self._prefill_d = make_prefill_fn(self.draft_config, self.sampler)
        self._draft_sampler = draft_sampler
        self._loops: dict[tuple, Any] = {}  # fused loop per stop-token set
        self.cache_dtype = cache_dtype

    def _loop(self, stop_tokens: tuple[int, ...]):
        if stop_tokens not in self._loops:
            self._loops[stop_tokens] = make_spec_decode_fn(
                self.draft_config, self.config, self.gamma, self.sampler,
                self._draft_sampler, stop_tokens,
            )
        return self._loops[stop_tokens]

    def generate(
        self,
        prompt_ids: np.ndarray,
        max_new_tokens: int,
        *,
        max_seq_len: int | None = None,
        seed: int = 0,
        stop_tokens: tuple[int, ...] = (),
    ) -> SpecResult:
        prompt_ids = jnp.asarray(prompt_ids, dtype=jnp.int32).reshape(1, -1)
        s = prompt_ids.shape[1]
        # rounds overshoot by up to γ+1 tokens before rollback trims them
        max_seq_len = max_seq_len or s + max_new_tokens + self.gamma + 1
        _check_capacity(s, max_new_tokens + self.gamma + 1, max_seq_len)

        key = jax.random.PRNGKey(seed)
        key, kp = jax.random.split(key)
        tcache = KVCache.init(self.config, 1, max_seq_len, dtype=self.cache_dtype)
        dcache = KVCache.init(self.draft_config, 1, max_seq_len, dtype=self.cache_dtype)

        t0_wall = time.perf_counter()
        tok, tcache, _ = self._prefill_t(self.params, prompt_ids, tcache, kp)
        _, dcache, _ = self._prefill_d(self.draft_params, prompt_ids, dcache, kp)
        int(tok[0])  # force
        ttft = time.perf_counter() - t0_wall

        # the whole speculative loop is ONE dispatch (lax.while_loop)
        t_dec = time.perf_counter()
        buf, total, rounds, accepted, dcache, tcache = self._loop(stop_tokens)(
            self.draft_params, self.params, tok, dcache, tcache, key,
            max_new_tokens,
        )
        buf = np.asarray(buf)  # forces completion (D2H)
        decode_s = time.perf_counter() - t_dec
        total, rounds, accepted = int(total), int(rounds), int(accepted)

        tokens = buf[: min(total, max_new_tokens)].astype(np.int32)
        if stop_tokens:
            hits = np.isin(tokens, stop_tokens).nonzero()[0]
            if hits.size:
                tokens = tokens[: hits[0] + 1]
        n_dec = total - 1  # tokens produced after the prefill token
        return SpecResult(
            tokens=tokens,
            ttft_s=ttft,
            decode_tokens_per_s=n_dec / decode_s if decode_s > 0 else float("nan"),
            num_generated=len(tokens),
            rounds=rounds,
            acceptance_rate=accepted / (rounds * self.gamma) if rounds else 0.0,
            tokens_per_round=n_dec / rounds if rounds else 0.0,
        )
