"""Speculative decoding: draft-and-verify autoregressive generation.

Framework extension (the reference decodes strictly one token per forward,
llama3.2_model.py:865-902).  A cheap *draft* model proposes γ tokens
autoregressively; the *target* model scores all of them in ONE forward
(prefill-shaped, MXU-friendly); accepted prefixes keep the target's exact
output distribution via the Leviathan et al. accept/resample rule:

    accept dᵢ with prob min(1, p(dᵢ)/q(dᵢ));
    on first rejection resample from norm(max(p − q, 0));
    if all γ accepted, sample a bonus token from p — so every round emits
    between 1 and γ+1 tokens and the sampled distribution is *identical*
    to decoding with the target alone (greedy: byte-identical output).

TPU-native shape: one jitted ``spec_round`` per (γ, sampler) — the draft
loop is a ``lax.scan``, verification is a single γ+1-token forward, and
rejected tokens are rolled back with ``cache.truncate`` (an O(1) bitmap
mask — the preallocated cache never moves).  p and q are the *filtered*
sampler distributions (``Sampler.filtered_logits``), so min-p/top-k/top-p
speculation is exact too, not just plain-softmax sampling.

The default draft is the int8-quantized target (quant.py) — "self
speculation": no second checkpoint, ~2× cheaper per draft step, and
high acceptance because the quantized model rarely disagrees with bf16.
A genuinely smaller draft model can be passed explicitly.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from llm_np_cp_tpu.cache import KVCache, align_capacity, truncate
from llm_np_cp_tpu.config import ModelConfig
from llm_np_cp_tpu.generate import _check_capacity, make_prefill_fn
from llm_np_cp_tpu.models.transformer import forward
from llm_np_cp_tpu.ops.sampling import Sampler

Params = dict[str, Any]


@dataclasses.dataclass
class SpecResult:
    tokens: np.ndarray  # [num_generated] (1-D prompt) or [B, num_generated]
    ttft_s: float
    decode_tokens_per_s: float  # aggregate over rows (== per-seq at bs=1)
    num_generated: int
    rounds: int
    acceptance_rate: float  # accepted draft tokens / proposed (active rows)
    tokens_per_round: float  # mean per active row


def truncated_draft(
    params: Params,
    config: ModelConfig,
    num_layers: int,
    *,
    bits: int | None = None,
) -> tuple[Params, ModelConfig]:
    """Layer-skip self-draft: the first ``num_layers`` decoder layers of
    the target plus its embedding / final norm / head, optionally
    quantized to ``bits``.

    No second checkpoint needed (the draft IS a prefix of the target, so
    vocab/tokenizer match by construction) and the draft's weight stream
    shrinks with the layer count — at 8/16 layers + int4 the draft step
    streams ~1/6 of the bf16 target.  Draft quality is what it is (the
    early layers were never trained to feed the head directly); the
    accept/resample rule keeps the OUTPUT distribution exactly the
    target's regardless, so a weak draft costs speed only, never
    correctness.  (Framework extension — the reference has no
    speculation at all, llama3.2_model.py:865-902.)
    """
    if not 0 < num_layers <= config.num_hidden_layers:
        raise ValueError(
            f"num_layers must be in 1..{config.num_hidden_layers}, got {num_layers}"
        )
    draft = dict(params)
    # stacked [L, ...] leaves: keep the first num_layers of each
    draft["layers"] = jax.tree.map(lambda x: x[:num_layers], params["layers"])
    draft_config = dataclasses.replace(config, num_hidden_layers=num_layers)
    if bits is not None:
        from llm_np_cp_tpu.quant import quantize_params

        draft = quantize_params(draft, bits=bits)
    return draft, draft_config


def _as_rows(length: jnp.ndarray, batch: int) -> jnp.ndarray:
    """Cache length as per-row [B] (broadcasting a scalar on first use)."""
    length = jnp.asarray(length, jnp.int32)
    return jnp.broadcast_to(length, (batch,)) if length.ndim == 0 else length


def _spec_round_core(
    draft_params: Params,
    target_params: Params,
    t0: jnp.ndarray,
    dcache: KVCache,
    tcache: KVCache,
    key: jax.Array,
    *,
    draft_config: ModelConfig,
    target_config: ModelConfig,
    gamma: int,
    sampler: Sampler,
    draft_sampler: Sampler,
    active: jnp.ndarray | None = None,
    pad_offsets: jnp.ndarray | None = None,
):
    """Traced body of one speculative round, batched over rows.

    t0: [B] int32 — the verified input token per row.  Every row drafts γ
    tokens and verifies them in one target forward; each row accepts its
    own prefix length n_b, and the caches roll back PER ROW (vector
    ``length`` — cache.truncate/update_layer handle [B] offsets), so rows
    at different acceptance rates advance independently.

    active: optional [B] bool — rows that already finished (hit a stop
    token / budget) are frozen: their count is 0 and their cache rows roll
    back to where they started, so they burn no capacity.

    pad_offsets: optional [B] int32 — per-row LEFT-pad amounts for ragged
    batches (generate_ragged); threaded into every forward so RoPE
    positions and causal masks stay row-exact.

    Returns (emitted [B, γ+1] (first count_b real per row), count [B],
    dcache, tcache, next_t0 [B]).
    """
    b = t0.shape[0]
    kd, ku, kc = jax.random.split(key, 3)
    t_base = _as_rows(tcache.length, b)
    d_base = _as_rows(dcache.length, b)
    tcache = tcache._replace(length=t_base)
    dcache = dcache._replace(length=d_base)

    # --- draft: γ+1 steps (the extra step's proposal is discarded but
    # leaves the draft cache covering every verified input, so the
    # post-round rollback target base+n+1 always exists)
    def dstep(carry, k):
        tok, dc = carry
        logits, dc = forward(
            draft_params, tok[:, None], draft_config, dc, logits_last_only=True,
            pad_offsets=pad_offsets,
        )
        fl = draft_sampler.filtered_logits(logits[:, -1])  # [B, V]
        nxt = jax.random.categorical(k, fl, axis=-1).astype(jnp.int32)
        return (nxt, dc), (nxt, jax.nn.softmax(fl, axis=-1))

    dkeys = jax.random.split(kd, gamma + 1)
    (_, dcache2), (drafts, qprobs) = lax.scan(dstep, (t0, dcache), dkeys)
    d = jnp.moveaxis(drafts[:gamma], 0, 1)  # [B, γ] proposals d_1..d_γ
    qp = jnp.moveaxis(qprobs, 0, 1)  # [B, γ+1, V]

    # --- target: verify all proposals in one forward
    inp = jnp.concatenate([t0[:, None], d], axis=1)  # [B, γ+1]
    tlogits, tcache2 = forward(
        target_params, inp, target_config, tcache, pad_offsets=pad_offsets
    )
    p = jax.nn.softmax(sampler.filtered_logits(tlogits), axis=-1)  # [B, γ+1, V]

    # --- accept/reject (multiplied form avoids div-by-zero; q(d) > 0
    # by construction since d was sampled from q)
    p_d = jnp.take_along_axis(p[:, :gamma], d[..., None], axis=-1)[..., 0]
    q_d = jnp.take_along_axis(qp[:, :gamma], d[..., None], axis=-1)[..., 0]
    u = jax.random.uniform(ku, (b, gamma), dtype=jnp.float32)
    accept = u * q_d < p_d  # [B, γ]
    n = jnp.where(
        jnp.all(accept, axis=-1), gamma, jnp.argmin(accept, axis=-1)
    )  # [B]

    # --- correction (n < γ: residual norm(max(p−q, 0))) or bonus
    # (n == γ: plain p) — unified by a zero row AT position γ (qp has
    # γ+1 rows; its last row is the discarded extra draft step's
    # distribution and must NOT leak into the bonus sample)
    q_pad = qp.at[:, gamma].set(0.0)
    sel = lambda a: jnp.take_along_axis(a, n[:, None, None], axis=1)[:, 0]  # [B, V]
    residual = jnp.maximum(sel(p) - sel(q_pad), 0.0)
    total = jnp.sum(residual, axis=-1, keepdims=True)
    dist = jnp.where(total > 0, residual / jnp.maximum(total, 1e-38), sel(p))
    c = jax.random.categorical(kc, jnp.log(dist + 1e-38), axis=-1).astype(jnp.int32)

    emitted = jnp.concatenate(
        [d, jnp.zeros((b, 1), jnp.int32)], axis=1
    ).at[jnp.arange(b), n].set(c)
    count = n + 1  # [B]
    next_t0 = c
    if active is not None:
        count = jnp.where(active, count, 0)
        next_t0 = jnp.where(active, next_t0, t0)

    # --- roll both caches back to the accepted inputs t0..d_n, per row
    tcache2 = truncate(tcache2, t_base + count)
    dcache2 = truncate(dcache2, d_base + count)
    return emitted, count, dcache2, tcache2, next_t0


def make_spec_round_fn(
    draft_config: ModelConfig,
    target_config: ModelConfig,
    gamma: int,
    sampler: Sampler,
    draft_sampler: Sampler | None = None,
):
    """One jitted speculative round (granular API; one dispatch per round).

    (draft_params, target_params, t0 [B], dcache, tcache, key) →
    (emitted [B, γ+1] (only the first ``count_b`` of each row are real),
    count [B], dcache, tcache, next_t0 [B]).

    Both caches are DONATED (updated in place); callers must rebind them
    from the return value and never reuse the inputs.  Cache ``length``
    comes back as a per-row [B] vector from the first round on.
    """
    from functools import partial

    return jax.jit(
        partial(
            _spec_round_core,
            draft_config=draft_config,
            target_config=target_config,
            gamma=gamma,
            sampler=sampler,
            draft_sampler=draft_sampler or sampler,
        ),
        donate_argnums=(3, 4),  # both caches update in place; callers rebind
    )


def make_spec_decode_fn(
    draft_config: ModelConfig,
    target_config: ModelConfig,
    gamma: int,
    sampler: Sampler,
    draft_sampler: Sampler | None = None,
    stop_tokens: tuple[int, ...] = (),
):
    """The fused loop: ALL speculative rounds in one ``lax.while_loop`` —
    a single device dispatch for the whole generation (per-round host
    sync costs a full transport RTT on a tunneled chip, same reason
    generate.py fuses its decode scan).  Batched: rows accept draft
    prefixes independently (per-row cache lengths); rows that hit their
    budget or a stop token freeze (count 0, caches pinned) while the rest
    keep going, and the loop ends when every row is done.

    (draft_params, target_params, t0 [B], dcache, tcache, key, max_new) →
    (buf [B, max_new+γ+1] (first ``total_b`` real per row, t0 included),
    total [B], rounds [B] (rounds each row was ACTIVE in), accepted,
    proposed (scalars, summed over active rows), dcache, tcache).
    """
    from functools import partial

    draft_sampler_ = draft_sampler or sampler
    stops = jnp.asarray(stop_tokens, dtype=jnp.int32) if stop_tokens else None

    @partial(jax.jit, static_argnums=(6,), donate_argnums=(3, 4))
    def spec_decode(
        draft_params: Params,
        target_params: Params,
        t0: jnp.ndarray,
        dcache: KVCache,
        tcache: KVCache,
        key: jax.Array,
        max_new: int,
        pad_offsets: jnp.ndarray | None = None,
    ):
        b = t0.shape[0]
        # per-row lengths from round one, so the while-carry type is stable
        dcache = dcache._replace(length=_as_rows(dcache.length, b))
        tcache = tcache._replace(length=_as_rows(tcache.length, b))
        buf = jnp.zeros((b, max_new + gamma + 1), jnp.int32).at[:, 0].set(t0)
        done0 = (
            jnp.any(t0[:, None] == stops[None, :], axis=-1)
            if stops is not None
            else jnp.zeros((b,), jnp.bool_)
        )
        state = (
            jnp.ones((b,), jnp.int32),  # total emitted per row (t0 included)
            done0,
            t0,
            dcache,
            tcache,
            key,
            buf,
            jnp.zeros((b,), jnp.int32),  # rounds each row was active in
            jnp.zeros((), jnp.int32),  # accepted draft tokens (active rows)
            jnp.zeros((), jnp.int32),  # proposed draft tokens (active rows)
        )

        def cond(state):
            total, done = state[0], state[1]
            return jnp.any((total < max_new) & ~done)

        def body(state):
            (total, done, t, dcache, tcache, key, buf, rounds, accepted,
             proposed) = state
            key, kr = jax.random.split(key)
            active = (total < max_new) & ~done
            emitted, count, dcache, tcache, t = _spec_round_core(
                draft_params, target_params, t, dcache, tcache, kr,
                draft_config=draft_config, target_config=target_config,
                gamma=gamma, sampler=sampler, draft_sampler=draft_sampler_,
                active=active, pad_offsets=pad_offsets,
            )
            # write the whole γ+1 window at each row's total; slots past
            # `count_b` are garbage overwritten next round (buf is oversized
            # by γ+1 for the tail; frozen rows write only past their data)
            buf = jax.vmap(
                lambda row, em, tot: lax.dynamic_update_slice(row, em, (tot,))
            )(buf, emitted, total)
            if stops is not None:
                real = jnp.arange(gamma + 1)[None, :] < count[:, None]
                done = done | jnp.any(
                    real[:, :, None]
                    & (emitted[:, :, None] == stops[None, None, :]),
                    axis=(1, 2),
                )
            return (
                total + count, done, t, dcache, tcache, key, buf,
                rounds + active.astype(jnp.int32),
                accepted + jnp.sum(jnp.maximum(count - 1, 0)),
                proposed + gamma * jnp.sum(active.astype(jnp.int32)),
            )

        (total, _, _, dcache, tcache, _, buf, rounds, accepted, proposed) = (
            lax.while_loop(cond, body, state)
        )
        return buf, total, rounds, accepted, proposed, dcache, tcache

    return spec_decode


class SpeculativeGenerator:
    """Owns the jitted prefill + spec-round programs.

    Batched: a [B, S] prompt runs B speculative streams in one program —
    rows accept draft prefixes independently via per-row cache lengths
    (cache.py vector ``length``), so a slow row never rolls back a fast
    one.  1-D prompts keep the original batch-1 surface.

    draft defaults to the int8-quantized target params (self-speculation);
    pass ``draft_params``/``draft_config`` for a separate small model
    (they must share the tokenizer/vocab).
    """

    def __init__(
        self,
        params: Params,
        config: ModelConfig,
        *,
        draft_params: Params | None = None,
        draft_config: ModelConfig | None = None,
        gamma: int = 4,
        sampler: Sampler | None = None,
        draft_sampler: Sampler | None = None,
        cache_dtype: jnp.dtype = jnp.bfloat16,
        prefill_chunk: int | None = None,
    ) -> None:
        if draft_params is None:
            from llm_np_cp_tpu.quant import is_quantized, quantize_params

            if is_quantized(params["layers"].get("q_proj")):
                # target already int8 — nothing cheaper to derive; a
                # perfect draft (p == q) still pipelines γ+1 tokens/round
                draft_params = params
            else:
                draft_params = quantize_params(params)
        self.params = params
        self.config = config
        self.draft_params = draft_params
        self.draft_config = draft_config or config
        self.gamma = gamma
        self.sampler = sampler or Sampler()
        if prefill_chunk:
            from llm_np_cp_tpu.generate import make_chunked_prefill_fn

            self._prefill_t = make_chunked_prefill_fn(
                config, self.sampler, prefill_chunk
            )
            self._prefill_d = make_chunked_prefill_fn(
                self.draft_config, self.sampler, prefill_chunk
            )
        else:
            self._prefill_t = make_prefill_fn(config, self.sampler)
            self._prefill_d = make_prefill_fn(self.draft_config, self.sampler)
        self._draft_sampler = draft_sampler
        self._loops: dict[tuple, Any] = {}  # fused loop per stop-token set
        self.cache_dtype = cache_dtype

    def _loop(self, stop_tokens: tuple[int, ...]):
        if stop_tokens not in self._loops:
            self._loops[stop_tokens] = make_spec_decode_fn(
                self.draft_config, self.config, self.gamma, self.sampler,
                self._draft_sampler, stop_tokens,
            )
        return self._loops[stop_tokens]

    def generate(
        self,
        prompt_ids: np.ndarray,
        max_new_tokens: int,
        *,
        max_seq_len: int | None = None,
        seed: int = 0,
        stop_tokens: tuple[int, ...] = (),
    ) -> SpecResult:
        prompt_ids = jnp.asarray(prompt_ids, dtype=jnp.int32)
        squeeze = prompt_ids.ndim == 1
        if squeeze:
            prompt_ids = prompt_ids[None, :]
        return self._run(
            prompt_ids, max_new_tokens, max_seq_len, seed, stop_tokens,
            squeeze=squeeze,
        )

    def generate_ragged(
        self,
        prompts: list[np.ndarray | list[int]],
        max_new_tokens: int,
        *,
        max_seq_len: int | None = None,
        seed: int = 0,
        stop_tokens: tuple[int, ...] = (),
    ) -> SpecResult:
        """Speculative generation over prompts of different lengths.

        Same left-pad contract as Generator.generate_ragged: rows pad on
        the LEFT, per-row ``pad_offsets`` keep RoPE positions and masks
        exact through every draft/verify forward, and the per-row cache
        lengths the accept/rollback machinery already uses handle the
        rest — each row behaves as if it ran alone (verified in tests).
        """
        from llm_np_cp_tpu.generate import Generator

        ids, mask, pads = Generator.left_pad(prompts)
        return self._run(
            jnp.asarray(ids), max_new_tokens, max_seq_len, seed, stop_tokens,
            attn_mask=jnp.asarray(mask), pad_offsets=jnp.asarray(pads),
        )

    def _run(
        self,
        prompt_ids: jnp.ndarray,
        max_new_tokens: int,
        max_seq_len: int | None,
        seed: int,
        stop_tokens: tuple[int, ...],
        *,
        attn_mask: jnp.ndarray | None = None,
        pad_offsets: jnp.ndarray | None = None,
        squeeze: bool = False,
    ) -> SpecResult:
        b, s = prompt_ids.shape
        # rounds overshoot by up to γ+1 tokens before rollback trims them
        max_seq_len = max_seq_len or s + max_new_tokens + self.gamma + 1
        _check_capacity(s, max_new_tokens + self.gamma + 1, max_seq_len)
        # 128-aligned capacities (same contract as Generator._init_cache):
        # extra slots are masked off, and the Pallas decode kernel's
        # kv-block search stays near its requested size.
        max_seq_len = align_capacity(max_seq_len)

        key = jax.random.PRNGKey(seed)
        key, kp = jax.random.split(key)
        tcache = KVCache.init(self.config, b, max_seq_len, dtype=self.cache_dtype)
        dcache = KVCache.init(self.draft_config, b, max_seq_len, dtype=self.cache_dtype)

        t0_wall = time.perf_counter()
        tok, tcache, _ = self._prefill_t(
            self.params, prompt_ids, tcache, kp, attn_mask, pad_offsets
        )
        _, dcache, _ = self._prefill_d(
            self.draft_params, prompt_ids, dcache, kp, attn_mask, pad_offsets
        )
        # force BOTH prefills (draft included) so its cost lands in TTFT,
        # not in the decode timer
        np.asarray(tok)
        np.asarray(dcache.length)
        ttft = time.perf_counter() - t0_wall

        # the whole speculative loop is ONE dispatch (lax.while_loop)
        t_dec = time.perf_counter()
        buf, total, rounds, accepted, proposed, dcache, tcache = self._loop(
            stop_tokens
        )(
            self.draft_params, self.params, tok, dcache, tcache, key,
            max_new_tokens, pad_offsets,
        )
        buf = np.asarray(buf)  # forces completion (D2H)
        decode_s = time.perf_counter() - t_dec
        total = np.asarray(total)
        rounds_b = np.asarray(rounds)
        accepted, proposed = int(accepted), int(proposed)

        tokens = buf[:, :max_new_tokens].astype(np.int32)
        # rate over the tokens actually RETURNED (the final round can
        # overshoot max_new_tokens by up to γ per row; those are trimmed
        # and must not inflate the reported rate)
        n_dec_b = np.minimum(total, max_new_tokens) - 1
        n_dec = int(n_dec_b.sum())
        if stop_tokens:
            from llm_np_cp_tpu.generate import _trim_after_stop

            tokens = _trim_after_stop(tokens, tuple(stop_tokens))
        if squeeze:
            tokens = tokens[0]
            if stop_tokens:
                hits = np.isin(tokens, stop_tokens).nonzero()[0]
                if hits.size:
                    tokens = tokens[: hits[0] + 1]
        act = rounds_b > 0
        return SpecResult(
            tokens=tokens,
            ttft_s=ttft,
            decode_tokens_per_s=n_dec / decode_s if decode_s > 0 else float("nan"),
            num_generated=tokens.shape[-1],
            rounds=int(rounds_b.max()),
            acceptance_rate=accepted / proposed if proposed else 0.0,
            # mean over rows of (tokens the row emitted / rounds it was
            # active in) — rows finishing early don't deflate the metric
            tokens_per_round=(
                float(np.mean(n_dec_b[act] / rounds_b[act])) if act.any() else 0.0
            ),
        )
