"""Mesh + sharding specs: the framework's distributed backbone.

The reference has zero distributed capability (SURVEY §2.9: no DP/TP/PP/SP,
no collective backend; its only "communication layer" is DLPack interop on
one GPU).  This module is the TPU-native replacement: a
``jax.sharding.Mesh`` over the chip grid and NamedShardings for every
parameter / cache / activation, compiled by XLA's GSPMD partitioner into
``psum`` / ``all_gather`` / ``reduce_scatter`` collectives that ride ICI
within a slice (and DCN across slices — same API, XLA picks transport).

Tensor-parallel layout (Megatron-style, per BASELINE north star):
- q/k/v/gate/up projections: column-sharded (output features) on "model"
- o/down projections: row-sharded (input features) on "model" — XLA inserts
  the psum for the partial sums
- embed/lm_head: vocab-sharded on "model"; logits stay vocab-sharded until
  sampling reduces them
- KV cache: kv-head axis sharded on "model" when divisible (Gemma-2-2B has
  4 KV heads — on an 8-way mesh the cache falls back to replication, the
  SURVEY §7 "TP + GQA" hard part; shard "seq" instead for long context,
  see parallel/ring_attention)
- batch axis: sharded on "data" everywhere

No hand-written collectives are needed for TP/DP — annotate + jit is the
whole programming model (the "How to Scale Your Model" recipe).  Explicit
``shard_map`` collectives appear only where GSPMD can't infer the schedule
(ring attention).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from llm_np_cp_tpu.config import ModelConfig

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
PIPE_AXIS = "pipe"
EXPERT_AXIS = "expert"


@dataclasses.dataclass(frozen=True)
class MeshPlan:
    """Static parallelism plan: how many ways each mesh axis is split.

    data: batch sharding (DP); model: tensor parallelism (TP);
    seq: sequence/context parallelism for the KV cache and ring attention;
    pipe: pipeline parallelism over the stacked layer axis (GPipe schedule,
    parallel/pipeline.py — training/no-cache forward only);
    expert: expert parallelism for MoE configs (ops/moe.py — the expert
    axis of router dispatch/combine einsums; GSPMD inserts the
    all-to-all-equivalent collectives).
    """

    data: int = 1
    model: int = 1
    seq: int = 1
    pipe: int = 1
    expert: int = 1

    @property
    def num_devices(self) -> int:
        return self.data * self.model * self.seq * self.pipe * self.expert

    def validate(self, config: ModelConfig) -> None:
        if self.model > 1:
            for dim, name in [
                (config.num_attention_heads, "num_attention_heads"),
                (config.intermediate_size, "intermediate_size"),
                (config.vocab_size, "vocab_size"),
            ]:
                if dim % self.model != 0:
                    raise ValueError(
                        f"{name}={dim} not divisible by model={self.model}"
                    )
        if self.pipe > 1 and config.num_hidden_layers % self.pipe != 0:
            raise ValueError(
                f"num_hidden_layers={config.num_hidden_layers} not divisible "
                f"by pipe={self.pipe}"
            )
        if self.expert > 1:
            if not config.is_moe:
                raise ValueError("expert>1 requires a MoE config")
            if config.num_local_experts % self.expert != 0:
                raise ValueError(
                    f"num_local_experts={config.num_local_experts} not "
                    f"divisible by expert={self.expert}"
                )


def parse_mesh_spec(text: str) -> MeshPlan:
    """CLI mesh syntax → MeshPlan, shared by the inference and training
    CLIs: named axes ``data=2,pipe=2,model=2`` (any of data/seq/model/
    pipe/expert) or the positional ``data,seq,model`` triple.  Raises
    SystemExit with a usage message on any malformed input (axis typos,
    non-integer values, wrong arity)."""
    axes = ("data", "seq", "model", "pipe", "expert")
    usage = (
        f"--mesh {text!r}: use named axes like data=2,pipe=2,model=2 "
        "(axes: data/seq/model/pipe/expert) or the positional "
        "data,seq,model triple"
    )
    kw = {}
    parts = [p for p in text.split(",") if p]
    try:
        if parts and all("=" in p for p in parts):
            for p in parts:
                name, _, val = p.partition("=")
                if name not in axes:
                    raise SystemExit(f"unknown mesh axis {name!r}; {usage}")
                kw[name] = int(val)
        elif len(parts) == 3 and not any("=" in p for p in parts):
            kw = dict(zip(("data", "seq", "model"), (int(p) for p in parts)))
        else:
            raise SystemExit(usage)
    except ValueError:
        raise SystemExit(usage) from None
    return MeshPlan(**kw)


def make_mesh(plan: MeshPlan, devices: list | None = None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    n = plan.num_devices
    if n > len(devices):
        raise ValueError(f"plan needs {n} devices, have {len(devices)}")
    grid = np.asarray(devices[:n]).reshape(
        plan.data, plan.pipe, plan.seq, plan.expert, plan.model
    )
    return Mesh(grid, (DATA_AXIS, PIPE_AXIS, SEQ_AXIS, EXPERT_AXIS, MODEL_AXIS))


def _kv_heads_shardable(config: ModelConfig, plan: MeshPlan) -> bool:
    return plan.model > 1 and config.num_key_value_heads % plan.model == 0


def kv_heads_shardable(config: ModelConfig, plan: MeshPlan) -> bool:
    """Public twin of the kv-head divisibility rule: True when the KV
    cache's head axis can be tensor-parallel over "model" (the SURVEY §7
    "TP + GQA" hard part — Gemma-2's 4 kv heads on an 8-way mesh fall
    back to replication).  The serve engine keys its Pallas-under-
    shard_map path on this."""
    return _kv_heads_shardable(config, plan)


def normalize_specs(specs: Any) -> Any:
    """Strip trailing ``None`` entries from every PartitionSpec leaf.

    ``P(None, None, 'model', None)`` and ``P(None, None, 'model')`` mean
    the same placement, but GSPMD emits the NORMALIZED spelling on jit
    outputs while hand-written specs usually carry the trailing None —
    and jit's compile cache compares shardings by spelling, so an array
    that round-trips through a step (pool slabs, the serve temp cache)
    would hit one spurious recompile on its second dispatch.  Serving
    pins its in-avals through this normalization."""

    def norm(spec: P) -> P:
        entries = list(spec)
        while entries and entries[-1] is None:
            entries.pop()
        return P(*entries)

    return jax.tree.map(norm, specs, is_leaf=lambda x: isinstance(x, P))


def paged_kv_specs(config: ModelConfig, plan: MeshPlan,
                   quantized: bool = False) -> Any:
    """PartitionSpecs for the serving block pool's ``PagedKV`` slabs
    ``[L, NB, BS, K, D]`` — the paged analogue of ``cache_specs``: the
    kv-head axis shards over "model" when divisible (same rule as the
    contiguous cache, one decision shared by both layouts), everything
    else — layer, block, in-block slot — stays unsharded so block
    tables remain plain replicated scalars and the scalar-prefetch
    kernels see per-shard-identical indices.  int8 scale pages
    ``[L, NB, BS, K]`` shard like the values minus D."""
    from llm_np_cp_tpu.serve.block_pool import PagedKV

    kv = MODEL_AXIS if _kv_heads_shardable(config, plan) else None
    scale = P(None, None, None, kv) if quantized else None
    return normalize_specs(PagedKV(
        k=P(None, None, None, kv, None),
        v=P(None, None, None, kv, None),
        k_scale=scale,
        v_scale=scale,
    ))


def param_specs(config: ModelConfig, plan: MeshPlan) -> dict[str, Any]:
    """PartitionSpec pytree matching models.transformer.param_shapes.

    The leading layer axis of stacked weights is sharded over "pipe" when
    pipeline parallelism is on (parallel/pipeline.py consumes the local
    block per stage); under plain ``forward`` (pipe=1) it stays unsharded
    (lax.scan consumes it).
    """
    m = MODEL_AXIS if plan.model > 1 else None
    kv = MODEL_AXIS if _kv_heads_shardable(config, plan) else None
    pp = PIPE_AXIS if plan.pipe > 1 else None
    layers = {
        "ln_attn_in": P(pp, None),
        "q_proj": P(pp, None, m),
        "k_proj": P(pp, None, kv),
        "v_proj": P(pp, None, kv),
        "o_proj": P(pp, m, None),
        "ln_mlp_in": P(pp, None),
        "gate_proj": P(pp, None, m),
        "up_proj": P(pp, None, m),
        "down_proj": P(pp, m, None),
    }
    if config.attention_bias:
        # biases follow their projection's output sharding; o_bias is added
        # after the row-parallel psum, so it stays replicated on "model"
        layers["q_bias"] = P(pp, m)
        layers["k_bias"] = P(pp, kv)
        layers["v_bias"] = P(pp, kv)
        layers["o_bias"] = P(pp, None)
    if config.mlp_bias:
        layers["gate_bias"] = P(pp, m)
        layers["up_bias"] = P(pp, m)
        layers["down_bias"] = P(pp, None)
    if config.is_moe:
        # expert weights [L, E, ...]: experts on "expert", feature dims on
        # "model" (EP × TP compose); the tiny router stays replicated
        ex = EXPERT_AXIS if plan.expert > 1 else None
        layers["router"] = P(pp, None, None)
        layers["gate_proj"] = P(pp, ex, None, m)
        layers["up_proj"] = P(pp, ex, None, m)
        layers["down_proj"] = P(pp, ex, m, None)
    if config.sandwich_norms:
        layers["ln_attn_out"] = P(pp, None)
        layers["ln_mlp_out"] = P(pp, None)
    specs: dict[str, Any] = {
        "embed_tokens": P(m, None),
        "layers": layers,
        "final_norm": P(None),
    }
    if not config.tie_word_embeddings:
        specs["lm_head"] = P(None, m)
    return specs


def cache_specs(config: ModelConfig, plan: MeshPlan, quantized: bool = False) -> Any:
    """KVCache sharding: [L, B, S, K, D] — batch on data, kv-heads on model
    (when divisible), seq on the seq axis for context parallelism.  The
    int8 cache's scale slabs [L, B, S, K] shard like the values minus D."""
    from llm_np_cp_tpu.cache import KVCache

    d = DATA_AXIS if plan.data > 1 else None
    kv = MODEL_AXIS if _kv_heads_shardable(config, plan) else None
    s = SEQ_AXIS if plan.seq > 1 else None
    scale = P(None, d, s, kv) if quantized else None
    return KVCache(
        k=P(None, d, s, kv, None),
        v=P(None, d, s, kv, None),
        valid=P(d, s),
        length=P(),
        k_scale=scale,
        v_scale=scale,
    )


def batch_spec(plan: MeshPlan) -> P:
    return P(DATA_AXIS if plan.data > 1 else None, None)


def to_shardings(mesh: Mesh, specs: Any) -> Any:
    """PartitionSpec pytree → NamedSharding pytree."""
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def _scale_spec(spec: P, leaf: dict) -> P:
    """PartitionSpec for a quantized leaf's scale tensor: the weight's spec
    with contracted (size-1 in the scale, >1 in the payload) axes cleared —
    a size-1 axis cannot be sharded."""
    from llm_np_cp_tpu.quant import payload_key

    q = leaf[payload_key(leaf)]
    s = leaf["s"]
    entries = list(spec) + [None] * (q.ndim - len(spec))
    return P(*[
        None if (s.shape[i] == 1 and q.shape[i] != 1) else entries[i]
        for i in range(q.ndim)
    ])


def shard_params(params: Any, config: ModelConfig, plan: MeshPlan, mesh: Mesh) -> Any:
    """Place an existing param pytree onto the mesh.

    Quantized leaves (quant.py ``{"q", "s"}`` dicts) are handled: the int8
    payload takes the weight's spec, the scale takes the same spec with
    contracted axes cleared — so int8 weights compose with TP/DP/PP/EP.
    """
    from llm_np_cp_tpu.quant import is_quantized

    plan.validate(config)
    specs = param_specs(config, plan)

    def place(spec: P, leaf: Any) -> Any:
        if is_quantized(leaf):
            from llm_np_cp_tpu.quant import payload_key

            pk = payload_key(leaf)
            return {
                pk: jax.device_put(leaf[pk], NamedSharding(mesh, spec)),
                "s": jax.device_put(
                    leaf["s"], NamedSharding(mesh, _scale_spec(spec, leaf))
                ),
            }
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree.map(
        place, specs, params,
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_cache(cache: Any, config: ModelConfig, plan: MeshPlan, mesh: Mesh) -> Any:
    shardings = to_shardings(
        mesh, cache_specs(config, plan, quantized=cache.k_scale is not None)
    )
    return jax.tree.map(jax.device_put, cache, shardings)
