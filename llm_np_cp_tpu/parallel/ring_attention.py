"""Ring attention: sequence-parallel causal self-attention over a mesh axis.

Long-context capability the reference entirely lacks (its long-sequence
story is an unbounded concat-grown cache and a fully materialized [S, S]
score matrix on one device — llama3.2_model.py:325-330, :467-469).  Here
the sequence axis is sharded across chips (mesh axis "seq"); each chip
keeps its local Q block resident and the K/V blocks rotate around the ring
one hop per step via ``lax.ppermute`` over ICI, with online-softmax
(running max / sum / accumulator) merging partial results — attention for
sequences that cannot fit on one chip, with O(S/n) peak score memory.

This is the one place the framework writes explicit collectives
(``shard_map`` + ``ppermute``) instead of letting GSPMD infer them: the
rotation schedule is a pipeline, not a data dependency XLA can discover.

Supports the same attention surface as ops.attention.gqa_attention: GQA
grouping, causal masking, sliding windows, logit softcapping.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from llm_np_cp_tpu.parallel.sharding import DATA_AXIS, MODEL_AXIS, SEQ_AXIS

NEG_INF = float(jnp.finfo(jnp.float32).min)


def _local_ring_attention(
    q: jnp.ndarray,  # [B, S_loc, H, D]   (this chip's query block)
    k: jnp.ndarray,  # [B, S_loc, K, D]   (rotating)
    v: jnp.ndarray,
    *,
    axis_name: str,
    num_shards: int,
    scale: float,
    logit_softcap: float | None,
    window: int | None,
) -> jnp.ndarray:
    b, s_loc, h, d = q.shape
    kh = k.shape[2]
    g = h // kh
    me = lax.axis_index(axis_name)

    q_pos = me * s_loc + jnp.arange(s_loc, dtype=jnp.int32)  # [S_loc]
    qg = q.astype(jnp.float32).reshape(b, s_loc, kh, g, d)

    m = jnp.full((b, kh, g, s_loc, 1), NEG_INF, dtype=jnp.float32)
    l = jnp.zeros((b, kh, g, s_loc, 1), dtype=jnp.float32)
    acc = jnp.zeros((b, kh, g, s_loc, d), dtype=jnp.float32)

    perm = [(j, (j + 1) % num_shards) for j in range(num_shards)]
    k_cur, v_cur = k, v

    for step in range(num_shards):
        src = (me - step) % num_shards  # owner of the block we now hold
        kv_pos = src * s_loc + jnp.arange(s_loc, dtype=jnp.int32)

        scores = jnp.einsum(
            "bqkgd,bskd->bkgqs", qg, k_cur.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        ) * scale
        if logit_softcap is not None:
            scores = jnp.tanh(scores / logit_softcap) * logit_softcap

        mask = kv_pos[None, :] <= q_pos[:, None]  # [S_loc, S_kv]
        if window is not None:
            mask &= (q_pos[:, None] - kv_pos[None, :]) < window
        scores = jnp.where(mask[None, None, None, :, :], scores, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(scores, axis=-1, keepdims=True))
        p = jnp.exp(scores - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.einsum(
            "bkgqs,bskd->bkgqd", p, v_cur.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        m = m_new

        if step < num_shards - 1:
            k_cur = lax.ppermute(k_cur, axis_name, perm)
            v_cur = lax.ppermute(v_cur, axis_name, perm)

    l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows (none in causal use)
    out = (acc / l).astype(q.dtype)  # [B, K, G, S_loc, D]
    return jnp.moveaxis(out, 3, 1).reshape(b, s_loc, h, d)


def _pad_seq(q, k, v, num_shards):
    """Pad the sequence axis up to a shardable multiple (static shapes —
    S is a trace-time constant).  Trailing pad slots sit at the HIGHEST
    global positions, so causal masking makes them invisible to every
    real query; callers slice the garbage pad-query rows back off.
    Without this, any prompt whose length doesn't divide the seq axis
    (i.e. nearly every real tokenized prompt) would be unservable."""
    pad = -q.shape[1] % num_shards
    if pad:
        widths = ((0, 0), (0, pad), (0, 0), (0, 0))
        q, k, v = jnp.pad(q, widths), jnp.pad(k, widths), jnp.pad(v, widths)
    return q, k, v, pad


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "axis_name", "scale", "logit_softcap", "window"),
)
def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    mesh: Mesh,
    axis_name: str = SEQ_AXIS,
    scale: float,
    logit_softcap: float | None = None,
    window: int | None = None,
) -> jnp.ndarray:
    """Causal self-attention with the sequence axis sharded over ``axis_name``.

    q [B, S, H, D], k/v [B, S, K, D] (global shapes; any S — padded up to
    the axis size internally) → [B, S, H, D].  Semantically identical to
    the single-chip path — verified against gqa_attention in tests on a
    virtual mesh.
    """
    num_shards = mesh.shape[axis_name]
    s = q.shape[1]
    q, k, v, pad = _pad_seq(q, k, v, num_shards)
    fn = jax.shard_map(
        functools.partial(
            _local_ring_attention,
            axis_name=axis_name,
            num_shards=num_shards,
            scale=scale,
            logit_softcap=logit_softcap,
            window=window,
        ),
        mesh=mesh,
        in_specs=(
            P(None, axis_name, None, None),
            P(None, axis_name, None, None),
            P(None, axis_name, None, None),
        ),
        out_specs=P(None, axis_name, None, None),
    )
    out = fn(q, k, v)
    return out[:, :s] if pad else out


def ring_attention_ctx(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    scale: float,
    logit_softcap: float | None = None,
    window: int | None = None,
) -> jnp.ndarray:
    """Ring attention over the AMBIENT mesh (``jax.set_mesh``) — the entry
    point ``models.transformer.forward`` uses for ``attn_impl="ring"``.

    Composes with the rest of the forward's GSPMD shardings: the batch dim
    stays on "data" (DP), and the head dims stay on "model" (TP) when both
    Q and KV head counts divide the model axis — otherwise heads are
    replicated inside the ring (correct, just not TP-local; Gemma-2's 4 KV
    heads on an 8-way model axis hit this).  The sequence dim is sharded on
    "seq"; each chip's K/V block rotates one hop per step over ICI.

    Requires fresh positions 0..S-1 (prefill / cache-less forward), like
    the flash path — ``forward`` enforces the boundary.
    """
    mesh = jax.sharding.get_abstract_mesh()
    if SEQ_AXIS not in mesh.shape or mesh.shape[SEQ_AXIS] < 2:
        raise ValueError(
            "attn_impl='ring' needs an ambient mesh (jax.set_mesh) with a "
            f"'{SEQ_AXIS}' axis of size >= 2; got mesh shape {dict(mesh.shape)}"
        )
    num_shards = mesh.shape[SEQ_AXIS]
    s = q.shape[1]
    q, k, v, pad = _pad_seq(q, k, v, num_shards)
    d = DATA_AXIS if mesh.shape.get(DATA_AXIS, 1) > 1 else None
    tp = mesh.shape.get(MODEL_AXIS, 1)
    m = (
        MODEL_AXIS
        if tp > 1 and q.shape[2] % tp == 0 and k.shape[2] % tp == 0
        else None
    )
    fn = jax.shard_map(
        functools.partial(
            _local_ring_attention,
            axis_name=SEQ_AXIS,
            num_shards=num_shards,
            scale=scale,
            logit_softcap=logit_softcap,
            window=window,
        ),
        in_specs=(
            P(d, SEQ_AXIS, m, None),
            P(d, SEQ_AXIS, m, None),
            P(d, SEQ_AXIS, m, None),
        ),
        out_specs=P(d, SEQ_AXIS, m, None),
    )
    out = fn(q, k, v)
    return out[:, :s] if pad else out
