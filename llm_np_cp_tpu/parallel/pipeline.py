"""GPipe-style pipeline parallelism over the "pipe" mesh axis.

The reference has no pipeline parallelism (SURVEY §2.9: a single
sequential layer loop, llama3.2_model.py:685-697).  This module is the
TPU-native design — no send/recv threads, no NCCL process groups, no
scheduler daemon.  The entire schedule is ONE traced program:

- stacked layer weights ``[L, ...]`` are sharded on their leading axis
  across P pipeline stages (``shard_map`` manual over "pipe" only; GSPMD
  keeps auto-partitioning DP/TP on the other mesh axes inside each stage);
- the batch is split into M microbatches; at step t, stage p runs
  microbatch t−p through its local layer block (``lax.scan``), then
  rotates activations one hop along the ring with ``lax.ppermute`` (ICI
  neighbor exchange — XLA overlaps it with the next stage's compute);
- M + P − 1 steps drain the pipeline; the last stage accumulates outputs,
  broadcast back with a masked ``psum``.

``jax.grad`` differentiates straight through the schedule (ppermute's
transpose is the reverse permutation), so the pipelined loss gives exact
GPipe gradients — no hand-written backward pass.

Embedding, final norm, and lm_head run outside the pipelined region,
replicated over "pipe" (sharded by DP/TP as usual): for decoder LLMs the
embed/head FLOPs are tiny next to L layer blocks, and keeping them out of
the ring avoids special first/last-stage weight placement.

Scope: training and cache-less forward (the reference's full-recompute
mode).  Cached decode composes with DP/TP/SP instead — PP adds latency to
autoregressive decode, which is why inference frameworks shard depth-wise
only under memory pressure.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from llm_np_cp_tpu.config import ModelConfig
from llm_np_cp_tpu.models.transformer import (
    embed_inputs,
    final_logits,
    run_decoder_layer,
)
from llm_np_cp_tpu.ops.activations import ACT2FN
from llm_np_cp_tpu.ops.attention import causal_mask
from llm_np_cp_tpu.ops.rope import rope_cos_sin
from llm_np_cp_tpu.parallel.sharding import PIPE_AXIS, MeshPlan

Params = dict[str, Any]


def _stage_schedule(
    local_layers: Params,
    local_sliding: jnp.ndarray,
    x_mb: jnp.ndarray,
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    mask_global: jnp.ndarray,
    mask_local: jnp.ndarray,
    *,
    config: ModelConfig,
    num_stages: int,
) -> jnp.ndarray:
    """Per-device body (runs inside shard_map, manual over "pipe").

    local_layers: this stage's ``[L/P, ...]`` weight block.
    x_mb: ``[M, mb, S, H]`` microbatched embeddings (replicated over pipe;
        only stage 0 reads them).
    cos/sin/masks: shared by every microbatch (uniform positions 0..S−1 —
        ragged batches are a cached-decode feature, out of PP scope).

    Returns ``([M, mb, S, H] final hidden states, moe_aux scalar)``, both
    replicated over "pipe".  The router aux loss is averaged over
    (layer, microbatch) pairs — per-microbatch balancing, the GShard
    per-group convention (it differs from the full-batch statistic the
    unpipelined path computes only through routing-fraction covariance
    across microbatches).
    """
    idx = lax.axis_index(PIPE_AXIS)
    num_micro = x_mb.shape[0]
    act = ACT2FN[config.hidden_act]

    def local_block(x: jnp.ndarray, ws: tuple) -> tuple[jnp.ndarray, jnp.ndarray]:
        w, sliding = ws
        x, _, _, moe_aux = run_decoder_layer(
            w, x, config=config, act=act, cos=cos, sin=sin,
            mask_global=mask_global, mask_local=mask_local, sliding=sliding,
        )
        return x, moe_aux

    def step(carry: tuple, t: jnp.ndarray) -> tuple[tuple, None]:
        ring_in, out, aux_sum = carry
        # stage 0 ingests microbatch t; later stages take the ring input
        x_in = jnp.where(
            idx == 0, x_mb[jnp.clip(t, 0, num_micro - 1)], ring_in
        )
        y, layer_aux = lax.scan(local_block, x_in, (local_layers, local_sliding))
        # stage p holds microbatch t−p; bubbles (outside [0, M)) are garbage
        # and must not pollute the router-loss accumulator
        real = (t >= idx) & (t - idx < num_micro)
        aux_sum = aux_sum + jnp.where(real, jnp.sum(layer_aux), 0.0)
        # the last stage finishes microbatch t−(P−1) at step t
        done = t - (num_stages - 1)
        oi = jnp.clip(done, 0, num_micro - 1)
        prev = lax.dynamic_index_in_dim(out, oi, 0, keepdims=False)
        val = jnp.where((idx == num_stages - 1) & (done >= 0), y, prev)
        out = lax.dynamic_update_index_in_dim(out, val, oi, 0)
        ring_out = lax.ppermute(
            y, PIPE_AXIS, [(i, (i + 1) % num_stages) for i in range(num_stages)]
        )
        return (ring_out, out, aux_sum), None

    steps = jnp.arange(num_micro + num_stages - 1)
    # the carries become pipe-varying on the first step (idx enters the
    # where); mark the zero inits varying so scan's carry types are stable
    varying = lambda a: lax.pcast(a, (PIPE_AXIS,), to="varying")
    ring0 = varying(jnp.zeros_like(x_mb[0]))
    out0 = varying(jnp.zeros_like(x_mb))
    aux0 = varying(jnp.zeros((), jnp.float32))
    (_, out, aux_sum), _ = lax.scan(step, (ring0, out0, aux0), steps)
    # broadcast the last stage's accumulator to every stage; mean the
    # router loss over all (layer, microbatch) pairs across stages
    out = lax.psum(
        jnp.where(idx == num_stages - 1, out, jnp.zeros_like(out)), PIPE_AXIS
    )
    moe_aux = lax.psum(aux_sum, PIPE_AXIS) / (
        config.num_hidden_layers * num_micro
    )
    return out, moe_aux


def pp_forward(
    params: Params,
    input_ids: jnp.ndarray,
    config: ModelConfig,
    plan: MeshPlan,
    mesh: Mesh,
    *,
    num_microbatches: int,
    logits_last_only: bool = False,
    output_router_losses: bool = False,
) -> jnp.ndarray | tuple[jnp.ndarray, jnp.ndarray]:
    """Cache-less forward with the layer stack pipelined over "pipe".

    input_ids: [B, S]; B must divide into ``num_microbatches`` equal
    microbatches (the microbatch is the pipeline's unit of work — more
    microbatches shrink the P−1-step bubble at the cost of smaller GEMMs).

    Returns logits [B, S, V] float32 (or [B, 1, V] when logits_last_only),
    numerically identical to ``models.transformer.forward`` with no cache;
    with ``output_router_losses`` also the MoE aux-loss scalar (averaged
    per microbatch — see _stage_schedule).
    """
    b, s = input_ids.shape
    num_stages = plan.pipe
    if config.num_hidden_layers % num_stages:
        raise ValueError(
            f"num_hidden_layers={config.num_hidden_layers} not divisible by "
            f"pipe={num_stages}"
        )
    if b % num_microbatches:
        raise ValueError(f"batch {b} not divisible by microbatches {num_microbatches}")
    mb = b // num_microbatches

    x = embed_inputs(params, input_ids, config)

    positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None, :], (mb, s))
    cos, sin = rope_cos_sin(positions, config, dtype=jnp.float32)
    mask_global = causal_mask(positions, positions)
    mask_local = (
        causal_mask(positions, positions, window=config.sliding_window)
        if config.sliding_window is not None
        else mask_global
    )
    is_sliding = jnp.array(
        [config.layer_is_sliding(i) for i in range(config.num_hidden_layers)],
        dtype=jnp.bool_,
    )

    x_mb = x.reshape(num_microbatches, mb, s, x.shape[-1])
    staged = jax.shard_map(
        partial(_stage_schedule, config=config, num_stages=num_stages),
        mesh=mesh,
        axis_names={PIPE_AXIS},
        in_specs=(P(PIPE_AXIS), P(PIPE_AXIS), P(), P(), P(), P(), P()),
        out_specs=(P(), P()),
    )
    out, moe_aux = staged(
        params["layers"], is_sliding, x_mb, cos, sin, mask_global, mask_local
    )
    hidden = out.reshape(b, s, x.shape[-1])
    logits = final_logits(params, hidden, config, last_only=logits_last_only)
    if output_router_losses:
        return logits, moe_aux
    return logits


def make_pp_loss_fn(
    config: ModelConfig, plan: MeshPlan, mesh: Mesh, *, num_microbatches: int
):
    """Pipelined causal-LM loss — same math as train.causal_lm_loss (the
    MoE router aux loss is included with its per-microbatch semantics)."""

    def loss_fn(
        params: Params, batch: jnp.ndarray, loss_mask: jnp.ndarray | None = None
    ) -> jnp.ndarray:
        inputs, targets = batch[:, :-1], batch[:, 1:]
        logits, moe_aux = pp_forward(
            params, inputs, config, plan, mesh,
            num_microbatches=num_microbatches, output_router_losses=True,
        )
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        if loss_mask is not None:
            loss = jnp.sum(nll * loss_mask) / jnp.maximum(jnp.sum(loss_mask), 1.0)
        else:
            loss = jnp.mean(nll)
        if config.is_moe:
            loss = loss + config.router_aux_loss_coef * moe_aux
        return loss

    return loss_fn


def make_pp_train_step(
    config: ModelConfig,
    optimizer: optax.GradientTransformation,
    plan: MeshPlan,
    mesh: Mesh,
    *,
    num_microbatches: int,
):
    """Jitted pipelined ``step(params, opt_state, batch) → (params,
    opt_state, loss)``.  Gradients flow backward through the ppermute ring
    (exact GPipe); optimizer update happens where each shard lives."""
    loss_fn = make_pp_loss_fn(config, plan, mesh, num_microbatches=num_microbatches)

    @jax.jit
    def step(params: Params, opt_state, batch: jnp.ndarray):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step
