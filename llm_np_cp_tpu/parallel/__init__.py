"""Parallelism: device meshes, sharding specs, and collective layouts.

The reference has no distributed path at all (SURVEY §2.9 — single process,
single device); this package is the capability the TPU build adds: tensor /
data / sequence parallelism expressed as ``jax.sharding`` NamedShardings
over a ``Mesh``, with XLA inserting ``psum`` / ``all_gather`` /
``ppermute`` collectives over ICI.
"""
