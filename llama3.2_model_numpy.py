#!/usr/bin/env python
"""Llama-3.2 NumPy entrypoint (reference-compatible name).

The reference's llama3.2_model_numpy.py is the CPU twin of the CuPy file
and the de-facto golden path (SURVEY §1); here it is a shim that defaults
to ``--backend=numpy`` (the fp32 oracle in
llm_np_cp_tpu/backends/numpy_ref.py) with the 1B default model the
reference uses (llama3.2_model_numpy.py:1050).
"""

import os
import sys

# BLAS thread pinning before any numpy work — the reference sets these at
# the very top of the file (llama3.2_model_numpy.py:4-9); honor an existing
# user setting.
for _v in ("OPENBLAS_NUM_THREADS", "OMP_NUM_THREADS", "MKL_NUM_THREADS"):
    os.environ.setdefault(_v, "16")

from llm_np_cp_tpu.cli import run

if __name__ == "__main__":
    argv = sys.argv[1:]
    if not any(a.startswith("--backend") for a in argv):
        argv = ["--backend=numpy", *argv]
    run(argv, default_model="meta-llama/Llama-3.2-1B")
