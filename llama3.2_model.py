#!/usr/bin/env python
"""Llama-3.2 entrypoint (reference-compatible name, llama3.2_model.py).

The reference file is a CuPy/CUDA notebook export defaulting to
meta-llama/Llama-3.2-3B on one GPU (llama3.2_model.py:1101-1109).  This
shim runs the same capability on the TPU-native framework:

    python llama3.2_model.py --backend=tpu --model meta-llama/Llama-3.2-3B
    python llama3.2_model.py --backend=numpy   # fp32 CPU oracle path

See ``python llama3.2_model.py --help`` for samplers, mesh sharding, dtype
and streaming options.
"""

from llm_np_cp_tpu.cli import run

if __name__ == "__main__":
    run(default_model="meta-llama/Llama-3.2-3B")
