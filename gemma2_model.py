#!/usr/bin/env python
"""Gemma-2 entrypoint (reference-compatible name, gemma2_model.py).

The reference file defaults to google/gemma-2-2b on one GPU
(gemma2_model.py:1159-1167) and silently drops attention-logit softcapping
and sliding-window attention (SURVEY §2.7); this framework implements both.

    python gemma2_model.py --backend=tpu --model google/gemma-2-2b
"""

from llm_np_cp_tpu.cli import run

if __name__ == "__main__":
    run(default_model="google/gemma-2-2b")
